"""Benchmark: BERT-large training throughput on one TPU chip.

The reference's headline benchmark is BERT-large pretraining throughput
(README.md:38-46, BASELINE.md); with one real chip available the honest
single-chip metric is train samples/sec (fwd+bwd+adam, bf16 compute,
seq 128 — GluonNLP phase-1 geometry, batch 64/device like the reference's
per-GPU batch).

``vs_baseline`` normalizes against a 40%-MFU target on the chip's peak
bf16 throughput — i.e. vs_baseline >= 1.0 means the compiled step reaches
the efficiency class the reference claims for its GPU stack (~90% scaling
of a well-fed device).  Prints ONE JSON line.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np


def _probe_devices(timeout_s: float = 180.0):
    """Device discovery with a watchdog: a dead accelerator tunnel must
    produce a JSON result, not a hang (the driver records this output)."""
    result = {}

    def probe():
        try:
            import jax

            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            result["error"] = repr(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in result:
        return result["devices"]
    print(
        json.dumps(
            {
                "metric": "bert_large_train_samples_per_sec_per_chip",
                "value": 0,
                "unit": "samples/s",
                "vs_baseline": 0,
                "extra": {
                    "error": result.get("error", f"device init exceeded {timeout_s}s (accelerator tunnel down?)")
                },
            }
        )
    )
    raise SystemExit(0)


def main() -> None:
    _probe_devices()
    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.models.transformer import (
        bert_large,
        build_train_step,
        init_params,
        shard_params,
    )
    from byteps_tpu.parallel.mesh_utils import make_training_mesh

    # 32/chip fits v5e 16GB HBM without remat (64 like the reference's
    # per-GPU batch needs rematerialization — TODO: jax.checkpoint path)
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    # measured config: batch 32 fits HBM without remat at 44.5% MFU;
    # BENCH_REMAT=1 + BENCH_BATCH=64 trades recompute for batch (validate
    # on hardware before making it the default)
    remat = os.environ.get("BENCH_REMAT", "0") == "1"

    cfg = bert_large(max_seq=seq, compute_dtype=jnp.bfloat16, remat=remat)
    mesh = make_training_mesh(1, {"dp": 1, "pp": 1, "sp": 1, "tp": 1})
    params = shard_params(init_params(cfg, seed=0, pp_size=1), cfg, mesh)
    tx = optax.adamw(1e-4)
    opt_state = jax.jit(tx.init)(params)
    step = build_train_step(cfg, mesh, tx, donate=True)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    )
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, axis=1))

    # warmup / compile
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    samples_per_sec = batch * steps / dt

    # model FLOPs per sample (fwd+bwd = 3x fwd): matmul params + attention
    D, L, V, S = cfg.d_model, cfg.n_layers, cfg.vocab_size, seq
    flops_per_sample = 6 * S * (12 * L * D * D + D * V) + 12 * L * S * S * D
    peak_bf16 = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))  # v5e chip
    mfu = samples_per_sec * flops_per_sample / peak_bf16
    baseline_samples_per_sec = 0.40 * peak_bf16 / flops_per_sample

    print(
        json.dumps(
            {
                "metric": "bert_large_train_samples_per_sec_per_chip",
                "value": round(samples_per_sec, 2),
                "unit": "samples/s",
                "vs_baseline": round(samples_per_sec / baseline_samples_per_sec, 4),
                "extra": {
                    "mfu": round(mfu, 4),
                    "batch": batch,
                    "seq": seq,
                    "steps": steps,
                    "loss": float(loss),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
