"""Benchmark: BERT-large training throughput on one TPU chip.

The reference's headline benchmark is BERT-large pretraining throughput
(README.md:38-46, BASELINE.md); with one real chip available the honest
single-chip metric is train samples/sec (fwd+bwd+adam, bf16 compute,
seq 128 — GluonNLP phase-1 geometry, batch 64/device like the reference's
per-GPU batch).

``vs_baseline`` normalizes against a 40%-MFU target on the chip's peak
bf16 throughput — i.e. vs_baseline >= 1.0 means the compiled step reaches
the efficiency class the reference claims for its GPU stack (~90% scaling
of a well-fed device).  Prints ONE JSON line.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np


_LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".bench_last_good.json")


def _probe_devices(timeout_s: float = 180.0):
    """Device discovery with a watchdog: a dead accelerator tunnel must
    produce a JSON result, not a hang (the driver records this output)."""
    result = {}

    def probe():
        try:
            import jax

            if os.environ.get("JAX_PLATFORMS"):
                # the env var alone does not stick when a plugin
                # preregisters another platform; pin it explicitly
                jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
            result["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            result["error"] = repr(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in result:
        return result["devices"]
    extra = {
        "error": result.get(
            "error", f"device init exceeded {timeout_s}s (accelerator tunnel down?)"
        )
    }
    # the tunnel to the chip comes and goes in this environment; surface the
    # last measurement that DID complete on hardware (value stays 0 — this
    # run measured nothing)
    try:
        with open(_LAST_GOOD_PATH) as f:
            extra["last_good"] = json.load(f)
    except (OSError, ValueError):  # missing OR truncated/corrupt cache
        pass
    print(
        json.dumps(
            {
                "metric": "bert_large_train_samples_per_sec_per_chip",
                "value": 0,
                "unit": "samples/s",
                "vs_baseline": 0,
                "extra": extra,
            }
        )
    )
    raise SystemExit(0)


def _is_oom(e: Exception) -> bool:
    return "RESOURCE_EXHAUSTED" in repr(e) or "out of memory" in repr(e).lower()


def _time_transformer_step(cfg, batch: int, seq: int, steps: int, warmup: int):
    """Build + compile + time one transformer train-step config.  All
    allocations live in THIS frame, so an OOM unwinds them before any
    retry at a smaller batch allocates its own copy.  Raises on failure."""
    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.models.transformer import (
        build_train_step,
        init_params,
        shard_params,
    )
    from byteps_tpu.parallel.mesh_utils import make_training_mesh

    mesh = make_training_mesh(1, {"dp": 1, "pp": 1, "sp": 1, "tp": 1})
    params = shard_params(init_params(cfg, seed=0, pp_size=1), cfg, mesh)
    tx = optax.adamw(1e-4)
    opt_state = jax.jit(tx.init)(params)
    step = build_train_step(cfg, mesh, tx, donate=True)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
    )
    targets = jnp.asarray(np.roll(np.asarray(tokens), -1, axis=1))

    for _ in range(warmup):  # warmup / compile
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return batch * steps / dt, float(loss)


def _run_config(batch: int, seq: int, steps: int, remat: bool):
    """Compile + time one train-step config.  Returns (samples/s, loss,
    cfg) on success, None on OOM, or ("error", msg) on any other failure
    (e.g. a transient through-tunnel compile error) so remaining configs
    still run."""
    import jax.numpy as jnp

    from byteps_tpu.models.transformer import bert_large

    try:
        cfg = bert_large(max_seq=seq, compute_dtype=jnp.bfloat16, remat=remat)
        sps, loss = _time_transformer_step(cfg, batch, seq, steps, warmup=3)
        return sps, loss, cfg
    except Exception as e:  # noqa: BLE001  (XlaRuntimeError / RESOURCE_EXHAUSTED)
        if _is_oom(e):
            return None
        # transient through-tunnel compile failures (HTTP 500s from the
        # remote compile service) must not kill configs that DO compile
        return ("error", f"{type(e).__name__}: {repr(e)[:120]}")


def _run_transformer_extra(cfg_fn, batches, seq: int, steps: int, peak_bf16: float):
    """Secondary transformer config (seq-512 flash etc.): returns a dict
    for extra.models, trying batches largest-first until one fits.  The
    timed body lives in _time_transformer_step so a failed attempt's
    device buffers unwind before the smaller batch allocates."""
    last_err = "untried"
    for batch in batches:
        try:
            cfg = cfg_fn()
            sps, _loss = _time_transformer_step(cfg, batch, seq, steps, warmup=2)
            D, L, V = cfg.d_model, cfg.n_layers, cfg.vocab_size
            flops = 6 * seq * (12 * L * D * D + D * V) + 12 * L * seq * seq * D
            return {
                "samples_per_sec": round(sps, 2),
                "mfu": round(sps * flops / peak_bf16, 4),
                "batch": batch,
                "seq": seq,
            }
        except Exception as e:  # noqa: BLE001
            if _is_oom(e):
                last_err = f"OOM@b{batch}"
                continue
            return {"error": f"{type(e).__name__}: {repr(e)[:120]}"}
    return {"error": last_err}


def _time_conv_step(model, batch: int, steps: int, hw: int):
    """Build + time one conv train-step config; allocations confined to
    this frame (see _time_transformer_step).  Raises on failure."""
    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.optim import build_flax_data_parallel_step
    from byteps_tpu.parallel.mesh_utils import make_training_mesh

    mesh = make_training_mesh(1, {"dp": 1, "pp": 1, "sp": 1, "tp": 1})
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, hw, hw, 3)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 1000, size=(batch,)).astype(np.int32))
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(variables["params"])
    step = build_flax_data_parallel_step(
        model.apply,
        lambda lg, lb: optax.softmax_cross_entropy_with_integer_labels(lg, lb).mean(),
        tx,
        mesh=mesh,
    )
    for _ in range(2):
        variables, opt_state, loss = step(variables, opt_state, (x, y))
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        variables, opt_state, loss = step(variables, opt_state, (x, y))
    jax.block_until_ready(loss)
    return batch * steps / (time.perf_counter() - t0)


def _run_conv_extra(model_name: str, batches, steps: int, hw: int = 224):
    """ResNet-50 / VGG-16 data-parallel train throughput (the reference's
    own benchmark models, docs/performance.md:3-12) on one chip."""
    import jax.numpy as jnp

    if model_name == "resnet50":
        from byteps_tpu.models.resnet import ResNet50

        model = ResNet50(dtype=jnp.bfloat16)
    else:
        from byteps_tpu.models.vgg import VGG16

        model = VGG16(dtype=jnp.bfloat16)

    last_err = "untried"
    for batch in batches:
        try:
            sps = _time_conv_step(model, batch, steps, hw)
            return {"samples_per_sec": round(sps, 2), "batch": batch, "hw": hw}
        except Exception as e:  # noqa: BLE001
            if _is_oom(e):
                last_err = f"OOM@b{batch}"
                continue
            return {"error": f"{type(e).__name__}: {repr(e)[:120]}"}
    return {"error": last_err}


def _with_timeout(fn, seconds: float, label: str):
    """Run ``fn`` on a watchdog thread: a wedged accelerator tunnel during
    a secondary bench must not lose the already-measured headline result
    (the same failure mode _probe_devices guards the probe against)."""
    box: dict = {}

    def run():
        try:
            box["result"] = fn()
        except Exception as e:  # noqa: BLE001
            box["result"] = {"error": f"{type(e).__name__}: {repr(e)[:120]}"}

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(seconds)
    if "result" not in box:
        return {"error": f"{label} exceeded {seconds:.0f}s (tunnel wedged?)"}
    return box["result"]


def _bench_extra_models(steps: int, peak_bf16: float) -> dict:
    """The reference benchmarks ResNet-50 and VGG-16 alongside BERT
    (docs/performance.md:3-12, BASELINE.json configs 2/4/5); seq-512
    configs exercise the Pallas flash path where attention dominates.
    Each model reports independently — one failure never hides the rest."""
    import jax.numpy as jnp

    from byteps_tpu.models.transformer import bert_large, gpt2_medium

    budget = float(os.environ.get("BENCH_EXTRA_TIMEOUT", "420"))
    models = {}
    models["resnet50"] = _with_timeout(
        lambda: _run_conv_extra("resnet50", (128, 64), steps), budget, "resnet50"
    )
    models["vgg16"] = _with_timeout(
        lambda: _run_conv_extra("vgg16", (64, 32), steps), budget, "vgg16"
    )
    models["bert_large_seq512_flash"] = _with_timeout(
        lambda: _run_transformer_extra(
            lambda: bert_large(
                max_seq=512, compute_dtype=jnp.bfloat16, remat=True, use_flash=True
            ),
            (32, 16), 512, steps, peak_bf16,
        ),
        budget, "bert_large_seq512_flash",
    )
    models["gpt2_medium_seq512_flash"] = _with_timeout(
        lambda: _run_transformer_extra(
            lambda: gpt2_medium(
                max_seq=512, compute_dtype=jnp.bfloat16, remat=True, use_flash=True
            ),
            (32, 16), 512, steps, peak_bf16,
        ),
        budget, "gpt2_medium_seq512_flash",
    )
    return models


def main() -> None:
    _probe_devices()

    seq = int(os.environ.get("BENCH_SEQ", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    if os.environ.get("BENCH_BATCH"):
        configs = [
            (int(os.environ["BENCH_BATCH"]), os.environ.get("BENCH_REMAT", "0") == "1")
        ]
    else:
        # try the measured-best configs plus the no-remat candidate (skips
        # the ~30% recompute FLOPs if activations fit); dense attention —
        # see TransformerConfig.use_flash.  Report the fastest that fits.
        configs = [(128, False), (128, True), (64, True)]

    tried = {}
    best = None
    for batch, remat in configs:
        res = _run_config(batch, seq, steps, remat)
        key = f"b{batch}_remat{int(remat)}"
        if res is None:
            tried[key] = "OOM"
            continue
        if isinstance(res, tuple) and res[0] == "error":
            tried[key] = res[1]
            continue
        sps, loss, mcfg = res
        tried[key] = round(sps, 2)
        if best is None or sps > best[0]:
            best = (sps, loss, batch, remat, mcfg)
    if best is None:
        # every config OOM'd or failed to compile: still emit the JSON
        # contract line (the driver records stdout, not tracebacks)
        extra = {"error": "no benchmark config completed", "configs_tried": tried}
        # A tunnel outage and a code regression must not look alike: if the
        # same non-tunnel-shaped exception type killed every config, this is
        # a persistent failure — flag it and exit nonzero so the driver (and
        # a human reading BENCH_r*.json) can tell them apart.
        errs = [v for v in tried.values() if isinstance(v, str) and v != "OOM"]
        # anchored tokens only: gRPC status codes are SHOUTY and distinctive;
        # a bare "500"/"internal" substring would also match e.g. a shape
        # (1500, 128) in a genuine regression's message
        transient_markers = (
            "UNAVAILABLE", "DEADLINE_EXCEEDED", "INTERNAL:", "HTTP 500",
            "tunnel", "Connection reset", "Socket closed",
            "Unable to initialize backend",
        )
        persistent = (
            len(errs) == len(tried)
            and len({e.split(":", 1)[0] for e in errs}) == 1
            and not any(m in e for e in errs for m in transient_markers)
        )
        extra["failure_class"] = "persistent" if persistent else "transient"
        try:
            with open(_LAST_GOOD_PATH) as f:
                extra["last_good"] = json.load(f)
        except (OSError, ValueError):
            pass
        print(
            json.dumps(
                {
                    "metric": "bert_large_train_samples_per_sec_per_chip",
                    "value": 0,
                    "unit": "samples/s",
                    "vs_baseline": 0,
                    "extra": extra,
                }
            )
        )
        raise SystemExit(1 if persistent else 0)
    samples_per_sec, loss, batch, remat, mcfg = best

    # model FLOPs per sample (fwd+bwd = 3x fwd): matmul params + attention
    D, L, V, S = mcfg.d_model, mcfg.n_layers, mcfg.vocab_size, seq
    flops_per_sample = 6 * S * (12 * L * D * D + D * V) + 12 * L * S * S * D
    peak_bf16 = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))  # v5e chip
    mfu = samples_per_sec * flops_per_sample / peak_bf16
    baseline_samples_per_sec = 0.40 * peak_bf16 / flops_per_sample

    payload = {
                "metric": "bert_large_train_samples_per_sec_per_chip",
                "value": round(samples_per_sec, 2),
                "unit": "samples/s",
                "vs_baseline": round(samples_per_sec / baseline_samples_per_sec, 4),
                "extra": {
                    "mfu": round(mfu, 4),
                    "batch": batch,
                    "remat": remat,
                    "seq": seq,
                    "steps": steps,
                    "loss": float(loss),
                    "configs_tried": tried,
                    "vs_baseline_definition": (
                        "fraction of a 40%-MFU target on this chip's peak "
                        "bf16 FLOPs (single-chip; self-chosen target). The "
                        "reference's own headline metric is multi-worker "
                        "scaling efficiency — see tools/scaling_bench.py "
                        "for that harness (>=85% north star)."
                    ),
                },
            }
    # persist the headline measurement BEFORE the secondary models run: a
    # tunnel wedge during the extras must not lose this run's result
    _save_last_good(payload)

    # breadth: the reference's other benchmark models (ResNet-50, VGG-16)
    # plus seq-512 flash-attention configs; secondary metrics only, the
    # headline stays BERT seq-128 for cross-round comparability
    if os.environ.get("BENCH_EXTRA_MODELS", "1") != "0":
        payload["extra"]["models"] = _bench_extra_models(
            int(os.environ.get("BENCH_EXTRA_STEPS", "8")), peak_bf16
        )
        _save_last_good(payload)
    print(json.dumps(payload))


def _save_last_good(payload: dict) -> None:
    try:
        import datetime

        tmp = _LAST_GOOD_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                dict(payload, measured_at=datetime.datetime.now(
                    datetime.timezone.utc).isoformat()),
                f,
            )
        os.replace(tmp, _LAST_GOOD_PATH)  # atomic: no truncated cache
    except OSError:
        pass


if __name__ == "__main__":
    main()
