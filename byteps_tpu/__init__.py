"""byteps_tpu — a TPU-native distributed training framework.

A from-scratch re-design of the capabilities of BytePS (bytedance/byteps,
OSDI'20) for TPUs: a Horovod-compatible named-tensor ``push_pull`` API,
hierarchical communication (XLA collectives over ICI inside a slice, a
parameter-server-style CPU aggregation service over DCN between slices),
tensor partitioning, priority-based communication scheduling, gradient
compression with error feedback and momentum, sync/async training, elastic
suspend/resume, and Chrome-trace profiling.

Public API parity surface (reference: byteps/common/__init__.py:52-139,
byteps/torch/__init__.py:226-266):

    init / shutdown / suspend / resume
    rank / size / local_rank / local_size
    declare_tensor / push_pull / push_pull_async / poll / synchronize
    DistributedOptimizer / broadcast_parameters / broadcast_object
    get_pushpull_speed

The compute data plane is JAX/XLA (psum_scatter + all_gather over a
``jax.sharding.Mesh``); the host-side runtime (scheduler, PS transport,
reducers, codecs) is native C++ reached via ctypes.
"""

from byteps_tpu.common.config import Config, get_config, reset_config
from byteps_tpu.common.registry import TensorRegistry, get_registry
from byteps_tpu.api import (
    init,
    shutdown,
    suspend,
    resume,
    rank,
    size,
    local_rank,
    local_size,
    declare_tensor,
    push_pull,
    push_pull_async,
    push_pull_rowsparse,
    push_pull_rowsparse_async,
    poll,
    synchronize,
    broadcast_parameters,
    broadcast_object,
    get_pushpull_speed,
    get_robustness_counters,
    get_metrics,
    get_metrics_text,
    set_compression_lr,
)
from byteps_tpu.common.types import DegradedError
from byteps_tpu.optim import DistributedOptimizer, distributed_optimizer

__version__ = "0.1.0"

_SUBMODULES = (
    "api", "optim", "checkpoint", "callbacks", "cross_barrier", "data",
    "mixed_precision", "profiler", "compression", "models", "ops",
    "parallel", "comm", "core", "common", "server", "launcher", "native",
    "haiku_plugin",
)


def __getattr__(name: str):
    """Lazy submodule access: ``bps.checkpoint.save(...)`` without an
    explicit import (heavy deps like orbax/torch load on first touch)."""
    if name in _SUBMODULES:
        import importlib

        mod = importlib.import_module(f"byteps_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'byteps_tpu' has no attribute {name!r}")

__all__ = [
    "Config",
    "get_config",
    "reset_config",
    "TensorRegistry",
    "get_registry",
    "init",
    "shutdown",
    "suspend",
    "resume",
    "rank",
    "size",
    "local_rank",
    "local_size",
    "declare_tensor",
    "push_pull",
    "push_pull_async",
    "push_pull_rowsparse",
    "push_pull_rowsparse_async",
    "poll",
    "synchronize",
    "broadcast_parameters",
    "broadcast_object",
    "get_pushpull_speed",
    "get_robustness_counters",
    "get_metrics",
    "get_metrics_text",
    "set_compression_lr",
    "DistributedOptimizer",
    "distributed_optimizer",
]
