"""Public Horovod-compatible API.

Parity surface with the reference's Python entry points
(common/__init__.py:52-139, torch/__init__.py:226-466, torch/ops.py:38-236).

Semantics on TPU (single-controller JAX):

- *Local* (intra-slice) reduction is device-side: use the traceable
  collectives (:mod:`byteps_tpu.comm.collectives`) or
  :class:`byteps_tpu.optim.DistributedOptimizer`, which compile to ICI
  collectives.  This replaces the reference's per-process NCCL ranks.
- *Cross-worker* (inter-host) reduction is what this module's host-level
  ``push_pull`` does: partition → stage to host → PS push/pull over DCN →
  back to device.  With one worker it is the identity, matching the
  reference's 1-worker semantics (tests/test_mxnet.py:30-126).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Optional

import numpy as np

from byteps_tpu.common.config import get_config
from byteps_tpu.common.registry import get_registry
from byteps_tpu.core.state import get_state, init_state, require_state, shutdown_state


def init(lazy: bool = True) -> None:
    """Initialize the runtime (byteps_init / byteps_lazy_init,
    operations.cc:41-94)."""
    init_state()


def shutdown() -> None:
    """Tear down threads and connections (byteps_shutdown,
    operations.cc:89-94)."""
    shutdown_state()


def suspend() -> None:
    """Elastic suspend: stop engine/PS but keep tensor declarations so a
    later resume() re-assigns identical keys (operations.cc:114-119)."""
    shutdown_state()


def resume(
    num_workers: Optional[int] = None,
    num_servers: Optional[int] = None,
    global_rank: Optional[int] = None,
) -> None:
    """Elastic resume: rewrite topology env then re-init and replay tensor
    declarations in original order (common/__init__.py:75-82,
    operations.cc:96-112, ReDeclareTensor global.cc:431-436)."""
    if num_workers is not None:
        os.environ["DMLC_NUM_WORKER"] = str(num_workers)
    if num_servers is not None:
        os.environ["DMLC_NUM_SERVER"] = str(num_servers)
    if global_rank is not None:
        os.environ["BYTEPS_GLOBAL_RANK"] = str(global_rank)
    st = get_state()
    st.resuming = True
    try:
        get_registry().redeclare_all()
        init_state(fresh_env=True)
    finally:
        st.resuming = False


def rank() -> int:
    """Global worker rank (common/__init__.py:96-103)."""
    cfg = get_config()
    return cfg.global_rank if cfg.global_rank is not None else cfg.worker_id


def size() -> int:
    """Number of workers (common/__init__.py:105-112)."""
    return get_config().num_worker


def local_rank() -> int:
    return get_config().local_rank


def local_size() -> int:
    return get_config().local_size


def declare_tensor(name: str, **kwargs: str) -> int:
    """Declare a named tensor ahead of communication, optionally carrying
    compression kwargs (byteps_declare_tensor, mxnet/ops.py:82-120);
    returns the stable declared key.

    Server-side optimizer (docs/architecture.md "Server-side
    optimizer"): ``byteps_server_opt="sgd"|"momentum"|"adam"`` declares
    the tensor's keys with a server-side update rule (workers push
    gradients, pull updated parameters), overriding the process-wide
    ``BYTEPS_SERVER_OPT``; ``byteps_server_opt_hp`` carries its
    hyperparams as a JSON string or a dict (dicts are canonicalized to
    JSON here — registry kwargs are strings on the wire)."""
    raw = kwargs.get("byteps_server_opt")
    if raw is not None:
        rule = str(raw).strip().lower()
        if rule and rule not in ("0", "false", "no", "off"):
            # fail at DECLARE, not at the first push's INIT: the rule
            # registry is local, so a typo'd name should not travel to
            # the server before erroring
            from byteps_tpu.server.update_rules import RULE_NAMES

            if rule not in RULE_NAMES:
                raise ValueError(
                    f"unknown server update rule {rule!r} "
                    f"(have {RULE_NAMES})"
                )
    ctx = get_registry().declare(name, **{
        k: (json.dumps(v, sort_keys=True) if isinstance(v, dict) else str(v))
        for k, v in kwargs.items()
    })
    return ctx.declared_key


def push_pull_async(
    tensor: Any,
    name: str,
    average: bool = True,
    priority: int = 0,
    version: int = 0,
) -> int:
    """Start a cross-worker push_pull; returns a pollable handle
    (byteps_push_pull / DoPushPull, torch/ops.cc:99-113).

    The result (same shape/dtype as input) is retrieved by
    :func:`synchronize`.
    """
    st = require_state()
    cfg = st.config
    get_registry().declare(name)
    handle = st.handles.allocate()
    if not cfg.is_distributed:
        # Non-distributed role set skips push/pull loops entirely
        # (operations.cc:46-53): identity.
        st.handles.mark_done(handle, tensor)
        return handle
    # The tensor is handed to the engine UN-materialized: device→host
    # staging happens per partition on the COPYD2H stage thread, so this
    # call returns while the device computation producing the gradient may
    # still be in flight (the reference's ready-event + COPYD2H stream
    # overlap, core_loops.cc:378-443).
    st.engine.submit(
        name=name,
        tensor=tensor,
        average=average,
        priority=priority,
        version=version,
        handle=handle,
    )
    return handle


def poll(handle: int) -> bool:
    """True when the async op has completed (ops.py poll, handle_manager)."""
    return require_state().handles.poll(handle)


def synchronize(handle: int) -> Any:
    """Block until completion and return the reduced tensor
    (ops.py:214-236)."""
    return require_state().handles.wait_and_clear(handle)


def push_pull(
    tensor: Any,
    name: str,
    average: bool = True,
    priority: int = 0,
) -> Any:
    """Synchronous cross-worker push_pull (sum over workers, then average
    when ``average=True``).

    ``name`` is required: it is the cross-process aggregation key, so it
    must be identical on every worker (an auto-generated per-process name
    could never match up).  The reference likewise keys on names
    (torch/__init__.py:139: ``Gradient.<param name>``).

    Degraded-step policy (docs/robustness.md): when the data plane
    degrades mid-step — a server died past its retry budget — the handle
    raises :class:`~byteps_tpu.common.types.DegradedError`.  With
    ``BYTEPS_DEGRADED_STEP_RETRIES`` > 0 this wrapper first routes the
    failure through the in-place recovery plane (the engine resyncs the
    live servers, replays the journaled pushes they never absorbed, and
    pulls the completed round — docs/robustness.md "healing flow"); only
    when in-place heal is impossible does it resubmit the step up to that
    many times (with backoff, so the elastic rebuild can land) through
    the full re-init barrier.  Resubmission is exactly-once safe — the
    abandoned round was never published and the next submit re-runs the
    key's init barrier.  Default 0: the error propagates and the
    training loop decides.
    """
    retries = get_config().degraded_step_retries
    if retries <= 0:
        return synchronize(
            push_pull_async(tensor, name, average=average, priority=priority)
        )
    from byteps_tpu.common.types import DegradedError
    from byteps_tpu.comm.retry import Backoff

    bo = Backoff(base=0.25, cap=2.0)
    for attempt in range(retries + 1):
        try:
            return synchronize(
                push_pull_async(tensor, name, average=average, priority=priority)
            )
        except (DegradedError, ConnectionError) as e:
            # ConnectionError covers the submit-time init barrier hitting
            # a not-yet-evicted dead server — same transient class, and
            # the user opted into step retries
            if attempt >= retries:
                raise
            if isinstance(e, DegradedError):
                # in-place heal first: if the degradation was one-sided
                # (every live peer sailed on), the journal replay
                # completes the abandoned round with its ORIGINAL
                # payloads and the pulled result is exactly the
                # fault-free one — no re-init barrier, peers never block
                st = require_state()
                if st.engine is not None:
                    healed = st.engine.heal_degraded(name, tensor, average)
                    if healed is not None:
                        return healed
            import time as _time

            _time.sleep(bo.next_delay())


def push_pull_rowsparse_async(
    indices: Any,
    values: Any,
    name: str,
    total_rows: int,
    average: bool = True,
    priority: int = 0,
) -> int:
    """Start a row-sparse push_pull (RequestType::kRowSparsePushPull,
    common.h:267-271): push ``values`` rows at ``indices`` of a
    ``(total_rows, row_len)`` tensor; the server scatter-sums all workers'
    rows into the dense store, and the result (same ``indices``, gathered
    after the round completes) is retrieved by :func:`synchronize` as a
    ``(len(indices), row_len)`` array — the embedding-gradient path."""
    st = require_state()
    cfg = st.config
    get_registry().declare(name)
    handle = st.handles.allocate()
    if not cfg.is_distributed:
        # same semantics as the 1-worker PS path — scatter-add then gather,
        # so duplicate indices accumulate and bad indices raise identically
        # (shared validator keeps the two paths in lockstep)
        from byteps_tpu.common.partition import validate_rowsparse

        idx, vals = validate_rowsparse(indices, values, total_rows)
        dense = np.zeros((total_rows, vals.shape[1]), dtype=vals.dtype)
        np.add.at(dense, idx, vals)
        st.handles.mark_done(handle, dense[idx])
        return handle
    st.engine.submit_rowsparse(
        name=name,
        indices=indices,
        values=values,
        total_rows=total_rows,
        average=average,
        priority=priority,
        version=0,
        handle=handle,
    )
    return handle


def push_pull_rowsparse(
    indices: Any,
    values: Any,
    name: str,
    total_rows: int,
    average: bool = True,
    priority: int = 0,
) -> Any:
    """Synchronous row-sparse push_pull; see
    :func:`push_pull_rowsparse_async`."""
    return synchronize(
        push_pull_rowsparse_async(
            indices, values, name, total_rows, average=average, priority=priority
        )
    )


def broadcast_parameters(params: Any, root_rank: int = 0) -> Any:
    """Sync a pytree of parameters from ``root_rank`` to all workers.

    Reference trick (torch/__init__.py:268-299): non-root zeroes its copy,
    then an unaveraged push_pull sum leaves root's values everywhere.
    """
    import jax

    st = require_state()
    if not st.config.is_distributed:
        return params

    # Launch every leaf async, then synchronize — overlaps all round-trips
    # the way the reference broadcasts with async handles
    # (torch/__init__.py:268-299).
    def start_leaf(path, leaf):
        name = "Parameter." + "/".join(str(p) for p in path)
        arr = np.asarray(leaf)
        if rank() != root_rank:
            arr = np.zeros_like(arr)
        return push_pull_async(arr, name=name, average=False)

    handles = jax.tree_util.tree_map_with_path(start_leaf, params)

    def finish_leaf(handle, leaf):
        out = synchronize(handle)
        return jax.numpy.asarray(out, dtype=leaf.dtype) if hasattr(leaf, "dtype") else out

    return jax.tree_util.tree_map(finish_leaf, handles, params)


def broadcast_object(obj: Any, root_rank: int = 0, name: str = "obj") -> Any:
    """Broadcast an arbitrary picklable object (broadcast_object,
    torch/__init__.py:302-466: cloudpickle → byte tensor → push_pull).
    Two-phase: length first, then payload, both as unaveraged sums with
    non-root contributing zeros."""
    st = require_state()
    if not st.config.is_distributed:
        return obj
    payload = pickle.dumps(obj) if rank() == root_rank else b""
    ln = np.array([len(payload)], dtype=np.int64)
    if rank() != root_rank:
        ln = np.zeros_like(ln)
    total = int(push_pull(ln, name=f"{name}.len", average=False)[0])
    buf = np.zeros(total, dtype=np.uint8)
    if rank() == root_rank:
        buf[:] = np.frombuffer(payload, dtype=np.uint8)
    out = push_pull(buf, name=f"{name}.data", average=False)
    return pickle.loads(np.asarray(out, dtype=np.uint8).tobytes())


def set_compression_lr(lr: float) -> None:
    """Propagate the optimizer's learning rate into error-feedback
    compressor chains (the reference's ``lr.s`` shared file,
    vanilla_error_feedback.h:44-58).  No-op when nothing is compressed
    or the engine isn't running."""
    st = require_state()
    if st.engine is not None:
        st.engine.set_compression_lr(lr)


def get_pushpull_speed() -> float:
    """Windowed push/pull MB/s (common/__init__.py:131-139)."""
    st = require_state()
    return st.telemetry.mbps() if st.telemetry else 0.0


def get_robustness_counters() -> dict:
    """Snapshot of the data-plane degradation counters: retries, deadline
    expiries, connection revivals, replay dedupes, observed evictions,
    injected chaos faults, and the recovery plane's ``resync_attempt`` /
    ``resync_replayed_rounds`` / ``resync_giveup`` heal outcomes
    (docs/robustness.md).  Process-wide; usable before :func:`init`
    (counters exist independently of runtime state).

    FLAT totals only, for back-compat — the per-peer dimension (which
    server a retry/deadline/revive hit) is in :func:`get_metrics` under
    ``counters_labeled`` (docs/observability.md)."""
    from byteps_tpu.core.telemetry import counters

    return counters().snapshot()


def get_metrics() -> dict:
    """Structured snapshot of the full metrics registry: flat + labeled
    counters, gauges, and histogram p50/p90/p99 summaries (RPC round
    trips, per-stage dwell, server sum/publish latency, fused pack
    density — the catalog lives in docs/observability.md).  Process-wide;
    usable before :func:`init`."""
    from byteps_tpu.core.telemetry import metrics

    return metrics().snapshot()


def get_metrics_text() -> str:
    """The Prometheus text exposition this process would serve on
    ``BYTEPS_METRICS_PORT`` — for logging a scrape without running the
    HTTP endpoint (docs/observability.md)."""
    from byteps_tpu.core.telemetry import metrics

    return metrics().render_prometheus()
