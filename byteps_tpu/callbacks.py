"""Training-loop callbacks — the Keras-plugin parity layer.

Re-design of the reference's shared Keras callbacks
(_keras/callbacks.py:23-195, keras/callbacks.py) for functional JAX
training loops: plain callables you invoke at the standard hook points
(train begin / epoch begin / batch end).

- :class:`BroadcastGlobalVariablesCallback` — one-shot param sync from
  root at train start (BroadcastGlobalVariablesCallbackImpl).
- :class:`MetricAverageCallback` — average logged metrics across workers
  at epoch end (MetricAverageCallbackImpl).
- :class:`LearningRateScheduleCallback` — multiplier-based LR schedule
  with optional staircase, matching the reference's semantics.
- :class:`LearningRateWarmupCallback` — linear warmup from lr/factor to
  lr over N epochs (LearningRateWarmupCallbackImpl).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

import byteps_tpu as bps


class BroadcastGlobalVariablesCallback:
    """Sync params (and optionally opt state) from root once, at the first
    hook invocation."""

    def __init__(self, root_rank: int = 0) -> None:
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, params: Any, opt_state: Any = None):
        if self._done:
            return params, opt_state
        self._done = True
        params = bps.broadcast_parameters(params, root_rank=self.root_rank)
        if opt_state is not None:
            from byteps_tpu.checkpoint import broadcast_optimizer_state

            opt_state = broadcast_optimizer_state(opt_state, root_rank=self.root_rank)
        return params, opt_state


class MetricAverageCallback:
    """Average a metrics dict across workers (each metric becomes the
    cross-worker mean)."""

    def on_epoch_end(self, metrics: Dict[str, float]) -> Dict[str, float]:
        out = {}
        for name, value in metrics.items():
            arr = np.asarray([float(value)], dtype=np.float64)
            out[name] = float(
                np.asarray(bps.push_pull(arr, name=f"Metric.{name}", average=True))[0]
            )
        return out


class LearningRateScheduleCallback:
    """lr(epoch) = initial_lr * multiplier(epoch).

    ``multiplier`` may be a constant (applied on [start_epoch, end_epoch))
    or a callable of the epoch; ``staircase`` floors the epoch passed to
    the callable.
    """

    def __init__(
        self,
        initial_lr: float,
        multiplier,
        start_epoch: int = 0,
        end_epoch: Optional[int] = None,
        staircase: bool = True,
    ) -> None:
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        if callable(multiplier):
            self._fn = multiplier
            self._const = None
        else:
            self._fn = None
            self._const = float(multiplier)

    def lr(self, epoch: float) -> Optional[float]:
        """Learning rate for (fractional) epoch; None when outside this
        callback's window."""
        if epoch < self.start_epoch:
            return None
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return None
        if self._const is not None:
            return self.initial_lr * self._const
        e = math.floor(epoch) if self.staircase else epoch
        return self.initial_lr * self._fn(e - self.start_epoch)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warmup from initial_lr/warmup_factor up to initial_lr over
    ``warmup_epochs`` (commonly paired with lr scaled by worker count —
    the 'gradual warmup' recipe the reference implements)."""

    def __init__(
        self,
        initial_lr: float,
        warmup_epochs: int = 5,
        momentum_correction: bool = False,
        steps_per_epoch: Optional[int] = None,
    ) -> None:
        if momentum_correction:
            raise NotImplementedError(
                "momentum_correction is not implemented yet; rescale the "
                "optimizer momentum manually during warmup (the reference "
                "applies m' = m * (lr_new/lr_old) each adjustment)"
            )
        self.warmup_epochs = warmup_epochs

        def mult(e: float) -> float:
            if warmup_epochs <= 0:
                return 1.0
            frac = min(1.0, (e + 1) / warmup_epochs)
            base = 1.0 / bps.size() if bps.size() else 1.0
            return base + (1.0 - base) * frac

        super().__init__(
            initial_lr, mult, start_epoch=0, end_epoch=warmup_epochs,
            staircase=False,
        )
