"""Checkpoint/resume support surface.

The reference has no checkpoint subsystem of its own — it delegates to the
framework and provides the post-restore re-sync primitives
(broadcast_parameters / broadcast_optimizer_state / broadcast_object,
torch/__init__.py:268-466; SURVEY §5.4 says to keep exactly that split).
Here the framework-side store is orbax; this module adds the BytePS-style
wrappers:

- save / restore  (orbax PyTreeCheckpointer)
- restore_and_broadcast — restore on the root worker then broadcast to all
  workers over the PS plane, the ``broadcast_parameters`` pattern
- broadcast_optimizer_state — pickles non-array state via broadcast_object
- write_shard / read_shard — byte-shard files in the wire lossless
  container (docs/gradient-compression.md "Lossless frame compression")
  with a CRC32C trailer: the same versioned codec that frames
  MIGRATE_STATE/RESYNC_STATE bodies shrinks on-disk state blobs, and a
  truncated or bit-flipped shard fails CLOSED on read (LosslessError /
  ValueError), never silently restores wrong bytes
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save(path: str, tree: Any, force: bool = True) -> None:
    """Save a pytree (params / full train state) to ``path``."""
    _checkpointer().save(os.path.abspath(path), tree, force=force)


def restore(path: str, template: Optional[Any] = None) -> Any:
    """Restore a pytree; ``template`` (same structure, abstract or concrete
    leaves) restores into matching dtypes/shardings."""
    if template is not None:
        return _checkpointer().restore(os.path.abspath(path), item=template)
    return _checkpointer().restore(os.path.abspath(path))


def write_shard(path: str, data: bytes) -> int:
    """Write one byte shard through the wire lossless container plus a
    CRC32C trailer (4 bytes, big-endian, over the container).  Returns
    the bytes written — callers can log the on-disk ratio.  Atomic via
    rename so a crash mid-write never leaves a torn shard behind."""
    from byteps_tpu.comm.transport import crc32c
    from byteps_tpu.compression.lossless import compress_frame

    import struct

    blob = compress_frame(bytes(data))
    blob += struct.pack("!I", crc32c(blob))
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(blob)


def read_shard(path: str) -> bytes:
    """Read a :func:`write_shard` file, fail-closed: a short file, a
    CRC mismatch, or a corrupt container raises (ValueError subclass)
    instead of returning damaged state."""
    from byteps_tpu.comm.transport import crc32c
    from byteps_tpu.compression.lossless import (
        LosslessError,
        decompress_frame,
    )

    import struct

    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < 4:
        raise LosslessError("shard file shorter than its CRC trailer")
    body, trailer = blob[:-4], blob[-4:]
    (want,) = struct.unpack("!I", trailer)
    if crc32c(body) != want:
        raise LosslessError("shard CRC32C mismatch")
    return decompress_frame(body)


def restore_and_broadcast(
    path: str, template: Any, root_rank: int = 0
) -> Any:
    """Elastic/multi-worker restore: only ``root_rank`` reads the
    checkpoint; every other worker receives the values via the PS broadcast
    (the zero-then-pushpull trick, torch/__init__.py:268-299).  All workers
    must pass an identically-structured ``template``."""
    import byteps_tpu as bps

    if bps.rank() == root_rank:
        tree = restore(path, template)
    else:
        tree = jax.tree_util.tree_map(np.zeros_like, template)
    return bps.broadcast_parameters(tree, root_rank=root_rank)


def broadcast_optimizer_state(
    opt_state: Any, root_rank: int = 0, name: str = "OptState"
) -> Any:
    """Re-sync optimizer state after restore (broadcast_optimizer_state,
    torch/__init__.py:302-466): array leaves ride broadcast_parameters
    under ``name``-prefixed keys, non-array leaves (python scalars, enums)
    ride broadcast_object so their types survive.

    Pass a distinct ``name`` when broadcasting more than one state tree in
    a process — tensor declarations are keyed by name, and two trees under
    the same prefix would collide.
    """
    import byteps_tpu as bps

    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    is_array = [hasattr(l, "dtype") and hasattr(l, "shape") for l in leaves]
    arrays = {
        f"{name}.{i}": np.asarray(l)
        for i, (l, a) in enumerate(zip(leaves, is_array)) if a
    }
    others = [l for l, a in zip(leaves, is_array) if not a]
    synced_arrays = bps.broadcast_parameters(arrays, root_rank=root_rank)
    synced_others = bps.broadcast_object(others, root_rank=root_rank, name=f"{name}.pkl")
    out_leaves, oi = [], 0
    for i, a in enumerate(is_array):
        if a:
            out_leaves.append(synced_arrays[f"{name}.{i}"])
        else:
            out_leaves.append(synced_others[oi])
            oi += 1
    return jax.tree_util.tree_unflatten(treedef, out_leaves)
