"""Communication plane.

Intra-slice: XLA collectives over ICI (replaces the reference's NCCL layer,
SURVEY §2.1 nccl_manager).  Inter-host: PS-style push/pull over DCN
(replaces ps-lite, SURVEY §2.4).
"""
