"""Chaos van: deterministic fault injection on the PS data plane.

Production BytePS assumes nodes die mid-training (ps-lite heartbeats +
elastic suspend/resume, SURVEY §5.3); this van lets one machine rehearse
those failures.  ``BYTEPS_VAN=chaos:<inner>`` wraps any fd-stream van
(``chaos:tcp``, ``chaos:uds``, ``chaos:shm``) and injects faults on
every data-plane connection — both directions, because the listener
wraps accepted sockets and the published address carries a ``chaos+``
prefix so dialing clients wrap theirs too (the same address-encoded
dispatch the shm van uses).

Faults are decided per FRAME — transport.py sends one framed message per
``sendall``/``sendmsg`` call — so a "drop" loses exactly one message
while the connection stays healthy, which is the case per-RPC deadlines
and retries exist for.  Classes:

- **drop**:       the frame never leaves; silence until a deadline fires.
- **delay**:      the frame is held up to ``BYTEPS_CHAOS_DELAY_MS``.
- **disconnect**: the connection is torn down (peer sees EOF/RST) — the
                  client's revive-and-retry path must heal it.
- **truncate**:   a prefix of the frame is sent, then the connection is
                  torn down — a crash mid-send; the peer must detect the
                  short frame, not parse garbage.
- **corrupt**:    the frame's magic byte is flipped before sending — the
                  peer's framing check rejects it and drops the
                  connection.  (This models link corruption that survives
                  to the app layer as frame desync.)
- **payload corrupt**: ONE seeded byte past the fixed 32-byte header
                  gets one bit flipped and the frame ships otherwise
                  intact — the most common real-DCN silent failure (bad
                  NIC/DRAM flipping bits that TCP's 16-bit checksum
                  misses).  Historically this module refused to inject
                  it because nothing could detect it; with the
                  end-to-end integrity plane (``BYTEPS_WIRE_CHECKSUM``,
                  docs/robustness.md "Wire integrity") a receiver
                  verifies the frame's CRC32C before any sum core or
                  demux sees it, so payload corruption is now an
                  injectable, testable fault class.  With checksums OFF
                  the flip passes silently — exactly the A/B that
                  proves detection is the checksum's doing, not luck.

Determinism: ``BYTEPS_CHAOS_SEED`` seeds a per-connection
``random.Random`` derived from ``(seed, connection_index)``, where the
index is a process-global counter — with a fixed seed and a fixed
connect order, the fault schedule replays exactly.

Knobs (probabilities in [0,1], applied per frame in the order drop →
disconnect → truncate → corrupt → payload corrupt; delay is rolled
independently):

    BYTEPS_CHAOS_SEED            int,   default 0
    BYTEPS_CHAOS_DROP            float, default 0
    BYTEPS_CHAOS_DISCONNECT      float, default 0
    BYTEPS_CHAOS_TRUNCATE        float, default 0
    BYTEPS_CHAOS_CORRUPT         float, default 0
    BYTEPS_CHAOS_PAYLOAD_CORRUPT float, default 0
    BYTEPS_CHAOS_DELAY           float, default 0
    BYTEPS_CHAOS_DELAY_MS        float, default 20 (max; uniform 0..max)

Targeting (one-sided failure rehearsal — docs/robustness.md "healing
flow"; all three compose):

    BYTEPS_CHAOS_OPS          comma-separated op codes (transport.Op
                              ints) or Op member names ("MIGRATE_STATE",
                              case-insensitive); only frames whose
                              header op matches are faulted (RESYNC and
                              migration frames are ordinary frames: name
                              23/24 or MIGRATE_STATE/WRONG_OWNER here to
                              fault the recovery or resharding plane
                              itself).  Empty = all ops.
    BYTEPS_CHAOS_TARGET_PORT  fault only connections dialed to — or
                              accepted by a listener bound at — this TCP
                              port (one server out of the fleet).  0 =
                              every connection.
    BYTEPS_CHAOS_FAULT_BUDGET process-global cap on TOTAL injected
                              faults; once spent, chaos passes through.
                              With DROP=1.0 this makes "exactly the
                              first N targeted frames die" a
                              deterministic schedule — how the resync
                              tests kill one worker's retry budget on
                              cue.  -1 (default) = unlimited.

Non-targeted frames consume no RNG rolls, so the schedule for targeted
frames stays reproducible per (seed, connection index) regardless of
surrounding traffic.  Every injected fault bumps a ``chaos_*``
robustness counter (core/telemetry.py), so tests can assert the
schedule actually fired.

Scheduler link (docs/robustness.md "Control-plane recovery"): with
``BYTEPS_CHAOS_SCHED=1`` under a chaos van, the CONTROL plane is
faulted too — node→scheduler dials wrap their socket
(:func:`wrap_control`) and the scheduler wraps accepted connections,
so ``BYTEPS_CHAOS_TARGET_PORT=<scheduler port>`` plus symbolic
``BYTEPS_CHAOS_OPS`` names (``REGISTER``/``PING``/``ADDRBOOK``/
``BARRIER``) make scheduler-link faults deterministically injectable.
Control connections draw from a SEPARATE connection-index counter, so
arming the flag never shifts the data plane's per-connection RNG
streams (existing seeded schedules replay unchanged).  Off (default):
the scheduler link is never faulted and control wire behavior is
byte-identical to a chaos-less run.
"""

from __future__ import annotations

import itertools
import os
import random
import socket
import threading
import time
from dataclasses import dataclass

from byteps_tpu.comm.van import CHAOS_PREFIX  # single source of the prefix

#: process-global connection index — (seed, index) keys each socket's RNG
_conn_counter = itertools.count()
_conn_counter_lock = threading.Lock()

#: SEPARATE index stream for control-plane (scheduler) connections:
#: arming BYTEPS_CHAOS_SCHED must not shift the data-plane sockets'
#: (seed, index)-keyed RNG streams, or every existing seeded schedule
#: would silently change.  Offset keeps the two streams' derived seeds
#: disjoint.
_ctrl_conn_counter = itertools.count(1 << 16)


def _next_conn_index() -> int:
    with _conn_counter_lock:
        return next(_conn_counter)


def _next_ctrl_conn_index() -> int:
    with _conn_counter_lock:
        return next(_ctrl_conn_counter)


def reset_conn_indices() -> None:
    """Restart both connection-index streams from their origins.

    The per-socket fault RNG is keyed by (seed, connection index), and
    the index is process-global — a seeded chaos schedule therefore
    depends on how many chaos connections EARLIER tests in the same
    process happened to open.  Deterministic chaos tests call this at
    setup so their schedule is canonical (indices from 0) no matter
    which sub-suite combination runs them — the order-dependence that
    made test_fusion's ``[native-s4]`` lane flake across pytest
    selections.  Test-harness only: live jobs never reset mid-run."""
    global _conn_counter, _ctrl_conn_counter
    with _conn_counter_lock:
        _conn_counter = itertools.count()
        _ctrl_conn_counter = itertools.count(1 << 16)


def control_chaos_enabled() -> bool:
    """True when the process opted the scheduler link into fault
    injection: a chaos van is selected AND ``BYTEPS_CHAOS_SCHED=1``."""
    return (
        os.environ.get("BYTEPS_VAN", "").startswith("chaos:")
        and os.environ.get("BYTEPS_CHAOS_SCHED", "0").lower()
        not in ("", "0", "false", "no", "off")
    )


def wrap_control(sock, peer_port: int):
    """Chaos-wrap one control-plane (node→scheduler) socket when
    :func:`control_chaos_enabled`; pass-through otherwise.  Targeting
    composes: ``BYTEPS_CHAOS_TARGET_PORT=<scheduler port>`` faults only
    the scheduler link, and ``BYTEPS_CHAOS_OPS`` can name the control
    ops (REGISTER/PING/ADDRBOOK/BARRIER)."""
    if not control_chaos_enabled():
        return sock
    return ChaosSocket(
        sock, ChaosParams.from_env(), _next_ctrl_conn_index(),
        peer_port=peer_port,
    )


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v not in (None, "") else default


def _parse_op(tok: str) -> int:
    """One BYTEPS_CHAOS_OPS token → wire op code.  Accepts the raw int
    ("25") or the transport.Op member name ("MIGRATE_STATE",
    case-insensitive) — deterministic tests naming the migration plane
    shouldn't have to hardcode its op numbers."""
    tok = tok.strip()
    try:
        return int(tok)
    except ValueError:
        from byteps_tpu.comm.transport import Op

        try:
            return int(Op[tok.upper()])
        except KeyError:
            raise ValueError(
                f"BYTEPS_CHAOS_OPS token {tok!r} is neither an op code "
                "nor a transport.Op name"
            ) from None


@dataclass(frozen=True)
class ChaosParams:
    seed: int = 0
    drop: float = 0.0
    disconnect: float = 0.0
    truncate: float = 0.0
    corrupt: float = 0.0
    #: seeded single-bit flip past the fixed 32-byte header (frame ships
    #: otherwise intact) — detectable ONLY by the CHECKSUM_FLAG integrity
    #: plane (docs/robustness.md "Wire integrity")
    payload_corrupt: float = 0.0
    delay: float = 0.0
    delay_ms: float = 20.0
    #: fault only frames with these header op codes (empty = all)
    ops: frozenset = frozenset()
    #: fault only connections to/from this TCP port (0 = all)
    target_port: int = 0

    @staticmethod
    def from_env() -> "ChaosParams":
        ops = frozenset(
            _parse_op(tok) for tok in
            os.environ.get("BYTEPS_CHAOS_OPS", "").split(",") if tok.strip()
        )
        return ChaosParams(
            seed=int(os.environ.get("BYTEPS_CHAOS_SEED", "0") or 0),
            drop=_env_float("BYTEPS_CHAOS_DROP", 0.0),
            disconnect=_env_float("BYTEPS_CHAOS_DISCONNECT", 0.0),
            truncate=_env_float("BYTEPS_CHAOS_TRUNCATE", 0.0),
            corrupt=_env_float("BYTEPS_CHAOS_CORRUPT", 0.0),
            payload_corrupt=_env_float("BYTEPS_CHAOS_PAYLOAD_CORRUPT", 0.0),
            delay=_env_float("BYTEPS_CHAOS_DELAY", 0.0),
            delay_ms=_env_float("BYTEPS_CHAOS_DELAY_MS", 20.0),
            ops=ops,
            target_port=int(
                os.environ.get("BYTEPS_CHAOS_TARGET_PORT", "0") or 0
            ),
        )


# --- process-global fault budget (BYTEPS_CHAOS_FAULT_BUDGET) --------------
#
# Counts TOTAL injected faults across every chaos connection in the
# process; once spent the chaos layer passes frames through untouched.
# Latched from env on first use; tests reset it explicitly.

_budget_lock = threading.Lock()
_budget_left: list = [None]  # [None] = unread; [-1] = unlimited


def reset_fault_budget(n=None) -> None:
    """Re-arm the process fault budget: ``n`` faults, or re-read
    ``BYTEPS_CHAOS_FAULT_BUDGET`` lazily when ``n`` is None."""
    with _budget_lock:
        _budget_left[0] = None if n is None else int(n)


def _budget_allows() -> bool:
    """Consume one unit of the fault budget; False = budget spent (the
    frame must pass through un-faulted)."""
    with _budget_lock:
        left = _budget_left[0]
        if left is None:
            left = int(
                os.environ.get("BYTEPS_CHAOS_FAULT_BUDGET", "-1") or -1
            )
        if left < 0:
            _budget_left[0] = left
            return True
        if left == 0:
            _budget_left[0] = 0
            return False
        _budget_left[0] = left - 1
        return True


class ChaosSocket:
    """Socket proxy injecting send-side faults at frame granularity.

    Exposes ``sendmsg`` so transport._send delivers header+payload as ONE
    call (the scatter-gather path) — a fault then hits a whole frame, not
    half of one.  Header-only messages arrive via ``sendall``, also one
    frame.  Receives and teardown pass straight through.
    """

    def __init__(self, sock, params: ChaosParams, conn_index: int,
                 peer_port: int = 0) -> None:
        self._sock = sock
        self._p = params
        # independent stream per connection, reproducible per (seed, index)
        self._rng = random.Random((params.seed << 20) ^ conn_index)
        self._send_lock = threading.Lock()  # fault decisions are ordered
        # one-sided targeting: with target_port set, only the connection
        # dialed to (or accepted at) that port is ever faulted
        self._targeted = (
            not params.target_port or peer_port == params.target_port
        )

    # --- fault engine -----------------------------------------------------
    def _bump(self, name: str, frame: bytes = b"") -> None:
        from byteps_tpu.core.telemetry import counters

        counters().bump(name)
        self._tag_span(name, frame)

    @staticmethod
    def _tag_span(name: str, frame: bytes) -> None:
        """Stamp the injected fault on the OWNING span (the trace context
        of the frame being faulted), so a rehearsed fault is
        distinguishable from an organic one on the merged timeline: the
        instant event shares the victim RPC's trace/span ids and carries
        ``injected: true`` (docs/observability.md)."""
        from byteps_tpu.core.tracing import get_process_tracer

        tracer = get_process_tracer()
        if tracer is None or not tracer.enabled:
            return
        args = {"fault": name, "injected": True}
        if len(frame) >= 48 and frame[2] & 0x80:  # status TRACE_FLAG
            import struct as _struct

            trace_id, span_id = _struct.unpack_from("!QQ", frame, 32)
            args["trace"] = format(trace_id, "x")
            args["span"] = format(span_id, "x")
        tracer.record_instant("chaos", name, args)

    def _die(self, reason: str) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        raise ConnectionError(f"chaos: injected {reason}")

    def _send_frame(self, data: bytes) -> None:
        p = self._p
        with self._send_lock:
            # targeting: an untargeted connection, or a frame whose
            # header op is outside the BYTEPS_CHAOS_OPS filter, passes
            # through WITHOUT consuming an RNG roll — the targeted
            # schedule stays reproducible regardless of other traffic
            if not self._targeted or (
                p.ops and (len(data) < 2 or data[1] not in p.ops)
            ):
                self._sock.sendall(data)
                return
            roll = self._rng.random()
            if roll < p.drop:
                if not _budget_allows():
                    self._sock.sendall(data)
                    return
                self._bump("chaos_drop", data)
                return
            roll -= p.drop
            if roll < p.disconnect:
                if not _budget_allows():
                    self._sock.sendall(data)
                    return
                self._bump("chaos_disconnect", data)
                self._die("disconnect")
            roll -= p.disconnect
            if roll < p.truncate:
                if not _budget_allows():
                    self._sock.sendall(data)
                    return
                self._bump("chaos_truncate", data)
                k = self._rng.randrange(0, max(1, len(data)))
                try:
                    self._sock.sendall(data[:k])
                except OSError:
                    pass
                self._die("truncated frame")
            roll -= p.truncate
            if roll < p.corrupt:
                if not _budget_allows():
                    self._sock.sendall(data)
                    return
                self._bump("chaos_corrupt", data)
                mangled = bytearray(data)
                if mangled:
                    mangled[0] ^= 0xFF  # flip the magic → framing rejects it
                self._sock.sendall(bytes(mangled))
                return
            roll -= p.corrupt
            if roll < p.payload_corrupt:
                # single-bit flip past the fixed 32-byte header (trace
                # block / checksum field / payload — all covered by the
                # CHECKSUM_FLAG CRC); a header-only frame has nothing to
                # flip and passes through untouched without spending
                # budget
                if len(data) <= 32 or not _budget_allows():
                    self._sock.sendall(data)
                    return
                self._bump("chaos_payload_corrupt", data)
                mangled = bytearray(data)
                idx = self._rng.randrange(32, len(mangled))
                mangled[idx] ^= 1 << self._rng.randrange(8)
                self._sock.sendall(bytes(mangled))
                return
            if (p.delay > 0 and self._rng.random() < p.delay
                    and _budget_allows()):
                self._bump("chaos_delay", data)
                time.sleep(self._rng.random() * p.delay_ms / 1e3)
            self._sock.sendall(data)

    # --- socket surface used by transport.py ------------------------------
    def sendall(self, data) -> None:
        self._send_frame(bytes(data))

    def sendmsg(self, bufs) -> int:
        # one frame: transport._send passes [header, payload]; joining keeps
        # the fault decision atomic per message (the copy is the chaos tax)
        frame = b"".join(bytes(b) for b in bufs)
        self._send_frame(frame)
        return len(frame)

    @property
    def family(self):
        return self._sock.family

    def recv(self, n: int) -> bytes:
        return self._sock.recv(n)

    def recv_into(self, buf, nbytes: int = 0) -> int:
        return self._sock.recv_into(buf, nbytes)

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def setsockopt(self, *a) -> None:
        self._sock.setsockopt(*a)

    def fileno(self) -> int:
        return self._sock.fileno()

    def shutdown(self, how: int = socket.SHUT_RDWR) -> None:
        try:
            self._sock.shutdown(how)
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class ChaosListener:
    """Accept wrapper: accepted connections get the chaos treatment, so
    server→worker frames (acks, pull responses) are faulted too.
    ``port`` is the bound listen port — with BYTEPS_CHAOS_TARGET_PORT
    set, only the one server bound there faults its response lanes."""

    def __init__(self, inner, params: ChaosParams, port: int = 0) -> None:
        self._inner = inner
        self._params = params
        self._port = port

    def accept(self):
        conn, addr = self._inner.accept()
        return (
            ChaosSocket(conn, self._params, _next_conn_index(),
                        peer_port=self._port),
            addr,
        )

    def shutdown(self, how: int = socket.SHUT_RDWR) -> None:
        try:
            self._inner.shutdown(how)
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._inner.close()
        except OSError:
            pass


def make_chaos_van(inner):
    """Build the chaos wrapper around an inner Van instance.

    Lives here (not van.py) so the van registry needs no chaos imports
    unless chaos is actually selected.
    """
    from byteps_tpu.comm.van import Van

    class ChaosVan(Van):
        name = f"chaos:{inner.name}"

        def __init__(self) -> None:
            self.inner = inner
            self.params = ChaosParams.from_env()

        def listen(self, host: str):
            lsock, phost, port = self.inner.listen(host)
            return (
                ChaosListener(lsock, self.params, port=port),
                CHAOS_PREFIX + phost,
                port,
            )

        def connect(self, host: str, port: int, timeout: float = 30.0):
            if host.startswith(CHAOS_PREFIX):
                host = host[len(CHAOS_PREFIX):]
            sock = self.inner.connect(host, port, timeout=timeout)
            return ChaosSocket(sock, self.params, _next_conn_index(),
                               peer_port=port)

    return ChaosVan()
