"""Intra-slice collectives — the NCCL-layer replacement.

The reference's intra-host data plane is ncclReduceScatter + ncclAllGather
on PCIe-switch-scoped communicators with hand-rolled CUDA-event sync
(core_loops.cc:190-317, nccl_manager.cc).  On TPU the whole layer is three
lines of lax: ``psum_scatter`` + ``all_gather`` over a mesh axis, compiled
by XLA onto ICI with automatic overlap — no events, no signal sockets, no
ready tables on the device path.

Two call styles:

- :func:`push_pull` — traceable; call inside ``shard_map``/``pjit`` with a
  bound mesh axis.  Mirrors the semantic of the reference's per-gradient
  push_pull (sum-then-average across the reduction axis).
- :func:`jit_push_pull_tree` — host-callable; builds (and caches) a jitted
  shard_map that reduces a whole pytree of per-device gradients.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.comm.mesh import DP_AXIS


def push_pull(
    x: jax.Array,
    axis_name: str = DP_AXIS,
    average: bool = True,
    mode: str = "psum",
    axis_size: Optional[int] = None,
) -> jax.Array:
    """Traceable all-reduce over a mesh axis.

    ``mode="psum"`` emits one fused all-reduce; ``mode="scatter_gather"``
    (requires static ``axis_size``) emits reduce-scatter + all-gather
    explicitly, mirroring the reference's two-phase NCCL strategy
    (core_loops.cc:232-268) — useful when the scattered form feeds a
    sharded optimizer (ZeRO-style) so the gather can be deferred.
    """
    if mode == "scatter_gather":
        if not axis_size:
            raise ValueError("scatter_gather mode needs static axis_size")
        flat = x.reshape(-1)
        pad = (-flat.size) % axis_size
        padded = jnp.pad(flat, (0, pad)) if pad else flat
        scat = lax.psum_scatter(padded, axis_name, scatter_dimension=0, tiled=True)
        red = lax.all_gather(scat, axis_name, axis=0, tiled=True)
        red = red[: flat.size].reshape(x.shape)
    else:
        red = lax.psum(x, axis_name)
    if average:
        red = red / lax.psum(1, axis_name)
    return red


def reduce_scatter(x: jax.Array, axis_name: str = DP_AXIS, average: bool = True) -> jax.Array:
    """Traceable reduce-scatter: each member keeps 1/N of the summed tensor
    (the reference's REDUCE stage output before PUSH, core_loops.cc:232-253).
    Requires leading dim divisible by the axis size."""
    out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if average:
        out = out / lax.psum(1, axis_name)
    return out


def all_gather(x: jax.Array, axis_name: str = DP_AXIS) -> jax.Array:
    """Traceable all-gather along dim 0 (BROADCAST stage,
    core_loops.cc:254-268)."""
    return lax.all_gather(x, axis_name, axis=0, tiled=True)


def broadcast(x: jax.Array, axis_name: str = DP_AXIS, root: int = 0) -> jax.Array:
    """Traceable broadcast from ``root`` along a mesh axis — the primitive
    under broadcast_parameters (torch/__init__.py:268-299): every member
    ends with root's value."""
    idx = lax.axis_index(axis_name)
    zeroed = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(zeroed, axis_name)


@functools.lru_cache(maxsize=32)
def _build_tree_reducer(mesh: Mesh, average: bool):
    axes = tuple(ax for ax in (DP_AXIS, "fsdp") if ax in mesh.shape)
    if not axes:
        raise ValueError(f"mesh {mesh} has no data-parallel axis")

    def reduce_leaf(g):
        red = g[0]  # drop the size-1 per-member leading axis
        for ax in axes:
            red = lax.psum(red, ax)
        if average:
            denom = 1
            for ax in axes:
                denom *= mesh.shape[ax]
            red = red / denom
        return red

    def reduce_tree(grads):
        return jax.tree_util.tree_map(reduce_leaf, grads)

    spec_in = P(axes)  # leaves stacked along leading device axis
    fn = jax.shard_map(
        reduce_tree,
        mesh=mesh,
        in_specs=spec_in,
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def jit_push_pull_tree(grads: Any, mesh: Mesh, average: bool = True) -> Any:
    """Reduce a pytree of *stacked per-member* gradients: each leaf has a
    leading axis of size dp; returns the tree with that axis reduced away.

    This is the host-callable analogue of looping push_pull over every
    gradient (torch/__init__.py:139-158) — except one jitted program reduces
    the whole tree so XLA can schedule all transfers together.
    """
    return _build_tree_reducer(mesh, average)(grads)
