"""Round journal: bounded worker-side record of emitted push payloads.

The recovery plane's sender-side half (docs/robustness.md "healing
flow").  The engine records every data-plane push it emits — key, round
version, Cantor-encoded cmd, the exact wire payload, and whether the
bytes left inside a fused pack — so a worker that exhausted its RPC
retries against a *live* server can later replay exactly the rounds that
server never absorbed (Op.RESYNC_QUERY tells it which) and rejoin in
place, with no global re-init barrier and no peer participation.

Bounded two ways, because gradients are big and recovery only ever needs
the recent past (the per-key round gate admits at most one in-flight
round per key, so a live server can be behind by at most one round per
key — extra depth is slack for pipelined multi-key jobs):

- ``BYTEPS_JOURNAL_ROUNDS`` — rounds retained per key (depth);
- ``BYTEPS_JOURNAL_BYTES`` — total payload bytes across all keys; the
  globally OLDEST recorded rounds are evicted first when exceeded.

Generation safety: entries replay only into the round numbering they
were recorded under.  The engine clears a key's entries whenever it
re-runs that key's init barrier (elastic resize, engine restart, forced
re-init) — a stale entry replayed into a re-numbered generation would
corrupt sums, so the journal must never outlive the numbering.

The payload is copied on record (the engine hands zero-copy views whose
buffers die with the task); that copy is the whole cost of the feature
on the hot path.

Server-side optimizer keys (docs/architecture.md "Server-side
optimizer") change nothing here: the journal records gradient pushes
exactly as for SUM keys, and replay safety is the server's exactly-once
ledger — a replayed push dedupes BEFORE it can count toward a round
barrier, so the server's update rule fires exactly once per completed
round no matter how many journaled retransmits land.  The seed round's
parameter push is journaled like any other; replaying it is harmless
for the same reason (the ledger already marks it summed).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True)
class JournalEntry:
    """One journaled push: the exact bytes (and framing metadata) the
    engine emitted for (key, version)."""

    version: int
    cmd: int
    payload: bytes
    fused: bool = False  # emitted inside an Op.FUSED pack (replay is
    #                      per-key unfused — the server sums identically)


class RoundJournal:
    """Thread-safe bounded (rounds/bytes) per-key push journal."""

    def __init__(self, max_rounds: int, max_bytes: int) -> None:
        self.max_rounds = max(1, int(max_rounds))
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()
        # key → {version: JournalEntry}, insertion-ordered per key
        self._entries: Dict[int, "OrderedDict[int, JournalEntry]"] = {}
        # global FIFO of (key, version) in record order — byte-cap
        # eviction drops the OLDEST round anywhere, not a random key's
        self._fifo: "OrderedDict[tuple, None]" = OrderedDict()
        self._bytes = 0
        self.evicted = 0  # rounds dropped by either bound (observability)

    def record(self, key: int, version: int, cmd: int, payload,
               fused: bool = False) -> None:
        """Record (or replace — an unfuse fallback re-emits the same
        round) one push's wire payload."""
        entry = JournalEntry(int(version), int(cmd), bytes(payload), fused)
        with self._lock:
            per = self._entries.get(key)
            if per is None:
                per = self._entries[key] = OrderedDict()
            old = per.pop(entry.version, None)
            if old is not None:
                self._bytes -= len(old.payload)
                self._fifo.pop((key, entry.version), None)
            per[entry.version] = entry
            self._fifo[(key, entry.version)] = None
            self._bytes += len(entry.payload)
            while len(per) > self.max_rounds:
                self._evict_locked(key, next(iter(per)))
            while self._bytes > self.max_bytes and self._fifo:
                ek, ev = next(iter(self._fifo))
                self._evict_locked(ek, ev)

    def _evict_locked(self, key: int, version: int) -> None:
        per = self._entries.get(key)
        if per is None:
            return
        dropped = per.pop(version, None)
        if dropped is not None:
            self._bytes -= len(dropped.payload)
            self.evicted += 1
        self._fifo.pop((key, version), None)
        if not per:
            del self._entries[key]

    def entries_after(self, key: int, version: int) -> List[JournalEntry]:
        """Journaled rounds of ``key`` NEWER than ``version`` (the
        server-reported absorbed watermark), oldest first — exactly what
        a resync replay must re-send."""
        with self._lock:
            per = self._entries.get(key)
            if per is None:
                return []
            return sorted(
                (e for e in per.values() if e.version > version),
                key=lambda e: e.version,
            )

    def keys(self) -> List[int]:
        with self._lock:
            return list(self._entries)

    def clear_key(self, key: int) -> None:
        """Drop a key's entries — called when its init barrier re-runs
        (round numbering restarts; stale entries must never replay)."""
        with self._lock:
            per = self._entries.pop(key, None)
            if not per:
                return
            for version, e in per.items():
                self._bytes -= len(e.payload)
                self._fifo.pop((key, version), None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._fifo.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "keys": len(self._entries),
                "rounds": len(self._fifo),
                "bytes": self._bytes,
                "evicted": self.evicted,
            }


#: process-global journal — the engine configures it at start (it owns
#: the config snapshot); the PS client's heal path reads it.  None =
#: journaling disabled (BYTEPS_JOURNAL_ROUNDS=0): resync still works but
#: can only heal give-ups whose pushes the server already absorbed.
_journal: Optional[RoundJournal] = None
_journal_lock = threading.Lock()


def configure_journal(max_rounds: int, max_bytes: int) -> Optional[RoundJournal]:
    """(Re)build the process journal from config; returns it (or None
    when disabled).  An engine restart reconfigures rather than appends —
    the old generation's entries must not survive into the new one."""
    global _journal
    with _journal_lock:
        _journal = (
            RoundJournal(max_rounds, max_bytes) if max_rounds > 0 else None
        )
        return _journal


def get_journal() -> Optional[RoundJournal]:
    with _journal_lock:
        return _journal
