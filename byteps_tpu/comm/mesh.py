"""Device-mesh construction and axis conventions.

The reference hand-builds a GPU topology (PCIe-switch-scoped NCCL comms,
nccl_manager.cc:129-165).  On TPU the topology is a logical
``jax.sharding.Mesh`` and XLA routes collectives over ICI; our job is only
to pick good logical axes:

    dp  — data parallel (gradient reduction axis; maps to the reference's
          whole raison d'être)
    fsdp— optional parameter-sharded DP (zero-style; new scope beyond
          reference parity, SURVEY §2.7)
    pp  — pipeline stages
    tp  — tensor parallel (megatron-style)
    sp  — sequence/context parallel (ring attention)
    ep  — expert parallel

``BYTEPS_TPU_MESH`` (e.g. ``"dp:2,tp:4"``) overrides the auto layout, which
is a single ``dp`` axis over all addressable devices — the reference's pure
data-parallel topology.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
FSDP_AXIS = "fsdp"
PP_AXIS = "pp"
TP_AXIS = "tp"
SP_AXIS = "sp"
EP_AXIS = "ep"

_lock = threading.Lock()
_global_mesh: Optional[Mesh] = None


def parse_mesh_spec(spec: str) -> List[Tuple[str, int]]:
    """Parse ``"dp:2,tp:4"`` into [("dp", 2), ("tp", 4)]."""
    out: List[Tuple[str, int]] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, _, num = item.partition(":")
        out.append((name.strip(), int(num)))
    return out


def build_mesh(
    spec: str = "", devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh from a spec string, defaulting to 1-D data parallel."""
    devices = list(devices if devices is not None else jax.devices())
    if not spec:
        return Mesh(np.array(devices), (DP_AXIS,))
    axes = parse_mesh_spec(spec)
    shape = [n for _, n in axes]
    total = int(np.prod(shape))
    if total != len(devices):
        raise ValueError(
            f"mesh spec {spec!r} wants {total} devices, have {len(devices)}"
        )
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(name for name, _ in axes))


def set_global_mesh(mesh: Mesh) -> None:
    global _global_mesh
    with _lock:
        _global_mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    with _lock:
        return _global_mesh


def require_mesh() -> Mesh:
    m = get_global_mesh()
    if m is None:
        raise RuntimeError("byteps_tpu not initialized: call byteps_tpu.init() first")
    return m


def dp_size(mesh: Optional[Mesh] = None) -> int:
    m = mesh or require_mesh()
    size = 1
    for ax in (DP_AXIS, FSDP_AXIS):
        if ax in m.shape:
            size *= m.shape[ax]
    return size


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over every data-ish axis present."""
    axes = tuple(ax for ax in (DP_AXIS, FSDP_AXIS) if ax in mesh.shape)
    return NamedSharding(mesh, P(axes if axes else None))
