"""Worker-side PS client — the KVWorker replacement.

ps-lite surface the core consumes (SURVEY §2.4): zero-copy ``ZPush``/
``ZPull`` with completion callbacks (core_loops.cc:571,609), key→server
routing (EncodeDefaultKey, global.cc:628-677), scheduler rendezvous +
global barrier (global.cc:289-294).

One TCP connection per server; a receiver thread per connection demuxes
responses by ``seq`` and fires callbacks — the callback thread then drives
the next pipeline stage, exactly like ps-lite's callback threads drive
FinishOrProceed.

Self-healing (docs/robustness.md): every data-plane RPC is retried with
exponential backoff + jitter when its connection dies (``BYTEPS_RPC_
RETRIES`` attempts after the first), transparently re-dialing a dead
server connection first (revival) — so an injected disconnect, a dropped
frame, or a server restart costs a retry, not a failed training step.
With ``BYTEPS_RPC_DEADLINE_S`` set, a per-attempt deadline additionally
catches HUNG servers: expiry tears the suspect connection down (so no
late response can race a retry into a caller's zero-copy sink) and the
normal dead-connection retry path heals it.  Pushes carry the worker's
rank in the header ``flags`` byte so the server dedupes replays —
retried summation stays exactly-once (see server.py).
"""

from __future__ import annotations

import itertools
import json
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from byteps_tpu.core.telemetry import counters, metrics

from byteps_tpu.common.config import Config
from byteps_tpu.common.hashing import assign_server
from byteps_tpu.common.types import RequestType, get_command_type
from byteps_tpu.comm.rendezvous import GROUP_ALL, GROUP_WORKERS, RESIZE_SEQ
from byteps_tpu.comm.transport import (
    Message,
    Op,
    _recv_exact,
    close_socket,
    connect,
    recv_message,
    send_message,
)

#: sentinel payload marking a response whose bytes were received directly
#: into the caller's registered sink buffer (zero-copy pull)
_ZERO_COPIED = object()


class _ServerConn:
    def __init__(self, host: str, port: int, streams: int = 1,
                 dial_timeout: float = 30.0) -> None:
        from byteps_tpu.comm.shaping import (
            maybe_shape,
            shaping_enabled,
            warn_native_bypass_once,
        )

        if streams > 1 and shaping_enabled():
            # each stripe would get its OWN virtual wire, silently scaling
            # the emulated link to N x BYTEPS_VAN_RATE_MBYTES_S — a shaped
            # link models one wire, so striping is forced off
            warn_native_bypass_once(
                "ignoring BYTEPS_TCP_STREAMS>1 (a shaped link is one wire)"
            )
            streams = 1
        # data-plane link: shaped when BYTEPS_VAN_DELAY_MS /
        # BYTEPS_VAN_RATE_MBYTES_S emulate a DCN link (shaping.py)
        self.sock = maybe_shape(connect(host, port, timeout=dial_timeout))
        self.send_lock = threading.Lock()
        # striped lanes (BYTEPS_TCP_STREAMS, tcp only): extra parallel
        # connections to the same server, each framed message riding ONE
        # lane chosen by key — per-key FIFO is preserved absolutely while
        # distinct partitions fan out over independent kernel streams (the
        # RDMA/UCX multi-lane van analogue, reference setup.py:312-330).
        # Lane 0 doubles as the control lane (init/register/liveness).
        from byteps_tpu.comm.van import SHM_PREFIX, UNIX_PREFIX, strip_chaos

        self.stripes = [(self.sock, self.send_lock)]
        if streams > 1 and not strip_chaos(host).startswith(
            (UNIX_PREFIX, SHM_PREFIX)
        ):
            try:
                for _ in range(streams - 1):
                    self.stripes.append(
                        (maybe_shape(connect(host, port, timeout=dial_timeout)),
                         threading.Lock())
                    )
            except (ConnectionError, OSError):
                for sock, _ in self.stripes[1:]:
                    close_socket(sock)
                close_socket(self.sock)
                raise
        self.cb_lock = threading.Lock()
        self.callbacks: Dict[int, Callable[[Message], None]] = {}
        #: seq → caller-owned buffer the response payload is received INTO
        #: (zero-copy pull; ps-lite ZPull-into-SArray parity)
        self.sinks: Dict[int, memoryview] = {}
        self.next_seq = 0
        self.recv_thread: Optional[threading.Thread] = None
        self.dead = False  # set once the LAST recv loop exits; cb_lock-guarded
        # receiver loops still running; the last one to exit runs the
        # mark_dead drain (see lane_exited)
        self._live_lanes = len(self.stripes)
        #: per-server label value for counter slices (the book index the
        #: conn was built for; "?" for stubs) — set by the caller
        self.server_label = "?"
        #: CRC mismatches across the whole striped connection (the
        #: BYTEPS_CHECKSUM_CONN_LIMIT escalation tally)
        self._ck_fails = 0

    def note_checksum_fail(self) -> int:
        """Account one checksum-rejected reply; returns the connection's
        running mismatch total (cb_lock-guarded: lanes race)."""
        with self.cb_lock:
            self._ck_fails += 1
            return self._ck_fails

    def lane_exited(self) -> bool:
        """Account one receiver loop's exit; True when it was the last.
        Only the LAST lane may drain callbacks: a sibling lane can still be
        mid-recv_into, writing a response payload into a caller's
        zero-copy sink — draining early would hand the caller a 'failed'
        buffer another thread is still filling."""
        with self.cb_lock:
            self._live_lanes -= 1
            return self._live_lanes <= 0

    def stripe_for(self, key: int):
        """(sock, send_lock) lane for a key — stable, so same-key requests
        stay ordered on one stream even when pipelined (async mode)."""
        return self.stripes[key % len(self.stripes)]

    def close_all(self) -> None:
        """Close every lane: one lane dying poisons the whole connection
        (a partially-striped server link would strand keyed requests)."""
        for sock, _ in self.stripes:
            close_socket(sock)

    def alloc_seq(
        self,
        cb: Callable[[Message], None],
        sink: Optional[memoryview] = None,
    ) -> int:
        """Register a response callback; returns -1 (after firing
        ``cb(None)``) if the connection already died — a request enqueued
        AFTER the recv loop drained pending callbacks would otherwise
        never fire and its caller would hang in synchronize()."""
        with self.cb_lock:
            if not self.dead:
                seq = self.next_seq
                self.next_seq += 1
                self.callbacks[seq] = cb
                if sink is not None:
                    self.sinks[seq] = sink
                return seq
        cb(None)  # outside the lock: callbacks run user code
        return -1

    def pop_cb(self, seq: int) -> Optional[Callable[[Message], None]]:
        with self.cb_lock:
            self.sinks.pop(seq, None)
            return self.callbacks.pop(seq, None)

    def peek_sink(self, seq: int) -> Optional[memoryview]:
        """The registered receive buffer for a response seq, WITHOUT
        popping the callback: the entry must stay registered until the
        payload is fully received, so a connection dying mid-payload still
        drains the callback with None (mark_dead) instead of losing it."""
        with self.cb_lock:
            return self.sinks.get(seq)

    def mark_dead(self):
        """Flag the connection dead and drain pending callbacks (fired
        with None by the caller).  New alloc_seq calls fail immediately."""
        with self.cb_lock:
            self.dead = True
            cbs = list(self.callbacks.values())
            self.callbacks.clear()
            self.sinks.clear()
            return cbs

    def send_msg(self, msg: Message) -> None:
        """Frame + send on the key's lane (per-key FIFO across stripes)."""
        sock, lock = self.stripe_for(msg.key)
        send_message(sock, msg, lock)


class _NativeServerConn:
    """C++ data-plane lanes behind the same surface as ``_ServerConn``.

    Framing, striping, seq demux, and payload receive — including
    zero-copy pull-into-caller-buffer — run on GIL-free native threads
    (native/ps_client.cc; the worker-plane split of core_loops.cc:
    538-618).  Python runs only per-completion callbacks.  Selected by
    ``BYTEPS_NATIVE_CLIENT=1`` for tcp/uds links; the shm van keeps the
    Python client (its bulk path is already syscall-free mmap memcpy).

    Locking: ``alloc_seq`` registers the Python callback under
    ``_lock`` in the same critical section as the native alloc, and the
    completion hook pops under the same lock — a drain racing a fresh
    alloc blocks until the callback is registered, so no completion can
    ever miss its callback."""

    def __init__(self, host: str, port: int, streams: int = 1,
                 on_zero_copy=None) -> None:
        import ctypes

        from byteps_tpu.comm.van import UNIX_PREFIX
        from byteps_tpu.native import BPSC_CALLBACK, get_lib

        lib = get_lib()
        if lib is None or not hasattr(lib, "bpsc_drain"):
            raise ConnectionError("native client library unavailable")
        kind = 1 if host.startswith(UNIX_PREFIX) else 0
        addr = host[len(UNIX_PREFIX):] if kind else host
        self._lib = lib
        self._ct = ctypes
        self._lock = threading.Lock()
        self._cbs: Dict[int, tuple] = {}  # seq → (cb, sink keep-alive)
        self.dead = False
        #: per-server label for counter slices (set by the caller)
        self.server_label = "?"
        # mirror of the C++ lanes' mismatch tally (fed by op=-3
        # notifications) so the conn-limit escalation is counted here
        # too — the lanes themselves read the same env at bpsc_create
        from byteps_tpu.comm.transport import checksum_conn_limit

        self._ck_fails = 0
        self._ck_limit = checksum_conn_limit()
        self._on_zero_copy = on_zero_copy
        h = lib.bpsc_create(addr.encode(), port, kind, streams)
        if h < 0:
            raise ConnectionError(
                f"native client connect failed: {host}:{port}"
            )
        self._h: Optional[int] = h
        #: trace-context-aware send (None on a stale .so: trace context
        #: is then silently dropped, the pre-parity behavior)
        self._send2 = getattr(lib, "bpsc_send2", None)
        # the lanes' per-attempt round-trip histogram
        # (native_rpc_round_trip_seconds, measured send syscall →
        # completion enqueue with no ctypes/drain batching in the
        # number) merges into the process registry through the
        # histogram-provider seam (docs/observability.md)
        self._hist_provider = None
        if self._send2 is not None:
            from byteps_tpu.core.telemetry import metrics
            from byteps_tpu.native import native_client_histograms

            self._hist_provider = lambda: native_client_histograms(h)
            metrics().register_hist_provider(self._hist_provider)
        # batched-delivery buffers (bpsc_drain): a record array + payload
        # arena reused across drains; the doorbell handler is serialized
        # by _drain_lock so concurrent lane doorbells can't share them
        from byteps_tpu.native import DRAIN_REC_DTYPE

        self._drain_lock = threading.Lock()
        self._recs = np.zeros(512, dtype=DRAIN_REC_DTYPE)
        self._arena = np.zeros(1 << 20, dtype=np.uint8)
        # the CFUNCTYPE object must outlive the native lanes or the
        # trampoline is freed under a live C thread
        self._c_cb = BPSC_CALLBACK(self._on_doorbell)
        lib.bpsc_set_cb(h, self._c_cb, None)

    def _on_doorbell(self, _ctx, op, status, flags, seq, key, cmd,
                     version, payload, length, zero_copied) -> None:
        """op=-2 doorbell: the C++ completion queue went non-empty —
        drain in bulk (one trampoline per BURST instead of per message;
        the ~10-30µs ctypes marshalling cost made per-message delivery
        measurably slower on many-small-message rounds, VAN_BENCH
        r4/r5).  Any other op is bpsc_close's final per-record flush
        (the handle is out of the registry by then, so drain cannot
        deliver) — dispatch it directly."""
        if op != -2:
            try:
                if op >= 0 and not zero_copied and length:
                    body = self._ct.string_at(payload, length)
                else:
                    body = b""
                self._dispatch(op, seq, length, zero_copied, 0, key, cmd,
                               version, status, flags, None, direct=body)
            except Exception:  # noqa: BLE001 — never unwind into C
                pass
            return
        try:
            with self._drain_lock:
                while self._drain_once():
                    pass
        except Exception:  # noqa: BLE001 — never unwind into the C lane
            # a failed drain (e.g. MemoryError growing the arena) cannot
            # retry: the doorbell only fires on empty→non-empty, so the
            # queue would strand every future completion.  The connection
            # is unusable — fail every pending request loudly instead of
            # hanging its waiters.
            self._fail_pending()

    def _fail_pending(self) -> None:
        with self._lock:
            self.dead = True
            entries = list(self._cbs.values())
            self._cbs.clear()
        for entry in entries:
            try:
                entry[0](None)
            except Exception:  # noqa: BLE001
                pass

    def _drain_once(self) -> bool:
        ct = self._ct
        n = self._lib.bpsc_drain(
            self._h,
            self._recs.ctypes.data_as(ct.c_void_p),
            len(self._recs),
            self._arena.ctypes.data_as(ct.c_void_p),
            self._arena.nbytes,
        )
        if n == 0:
            return False
        if n < 0:  # first payload exceeds the arena: grow and retry
            self._arena = np.zeros(
                max(-int(n), 2 * self._arena.nbytes), dtype=np.uint8
            )
            return True
        # bulk field extraction: one vectorized .tolist() per column
        # instead of per-record numpy void indexing (~1µs per field
        # access adds up fast on small-message bursts)
        r = self._recs
        ops = r["op"][:n].tolist()
        seqs = r["seq"][:n].tolist()
        lens = r["len"][:n].tolist()
        zcs = r["zc"][:n].tolist()
        offs = r["off"][:n].tolist()
        keys = r["key"][:n].tolist()
        cmds = r["cmd"][:n].tolist()
        vers = r["version"][:n].tolist()
        stats = r["status"][:n].tolist()
        flags = r["flags"][:n].tolist()
        arena = self._arena
        for i in range(n):
            try:
                self._dispatch(
                    ops[i], seqs[i], lens[i], zcs[i], offs[i], keys[i],
                    cmds[i], vers[i], stats[i], flags[i], arena,
                )
            except Exception:  # noqa: BLE001
                # one bad callback must not strand the rest of the batch:
                # the doorbell only fires on empty→non-empty, so an
                # aborted drain would leave queued messages waiting
                # forever
                pass
        return True

    def _dispatch(self, op, seq, length, zc, off, key, cmd, version,
                  status, flags, arena, direct: Optional[bytes] = None) -> None:
        if op == -3:
            # corrupt-frame notification from the native recv lanes
            # (docs/robustness.md "Wire integrity"): the corrupt reply
            # was dropped IN C++ before the demux and the pending entry
            # stays registered (deadline/retry re-fetches) — this record
            # only carries the count to the telemetry plane.  The
            # corrupt frame's op rides in ``cmd``; ``status`` says which
            # validator rejected it (0 = CRC32C, 1 = lossless decode).
            try:
                opname = Op(cmd).name if cmd else "?"
            except ValueError:
                opname = str(cmd)
            counters().bump(
                "wire_lossless_fail" if status == 1 else "wire_checksum_fail",
                labels={
                    "side": "client", "op": opname,
                    "server": self.server_label,
                })
            self._ck_fails += 1
            if self._ck_limit and self._ck_fails == self._ck_limit:
                # the C++ lane breaks at exactly this count: record the
                # quarantine once, like the Python recv lanes do
                counters().bump("wire_checksum_conn_drop")
            return
        with self._lock:
            if op < 0:  # the connection died with this seq pending
                self.dead = True
            entry = self._cbs.pop(seq, None)
        if entry is None:
            return
        cb = entry[0]
        if op < 0:
            cb(None)
            return
        if zc:
            body = _ZERO_COPIED
            if self._on_zero_copy is not None:
                self._on_zero_copy()
        elif direct is not None:  # close-flush path: bytes already copied
            body = direct
        elif length:
            body = arena[off : off + length].tobytes()
        else:
            body = b""
        cb(Message(Op(op), key=key, payload=body, seq=seq, cmd=cmd,
                   version=version, status=status, flags=flags))

    def alloc_seq(self, cb, sink: Optional[memoryview] = None) -> int:
        sink_ptr, sink_len, keep = None, 0, None
        if sink is not None:
            # export the caller's writable buffer; the native lane
            # receives the response payload straight into it
            keep = (self._ct.c_ubyte * len(sink)).from_buffer(sink)
            sink_ptr = self._ct.addressof(keep)
            sink_len = len(sink)
        with self._lock:
            if not self.dead and self._h is not None:
                seq = self._lib.bpsc_alloc_seq(self._h, sink_ptr, sink_len)
                if seq >= 0:
                    self._cbs[seq] = (cb, keep)
                    return seq
        cb(None)  # outside the lock: callbacks run user code
        return -1

    def send_msg(self, msg: Message) -> None:
        payload = msg.payload or b""
        n = len(payload)
        ptr = None
        if n:
            # no-copy pointer for bytes / bytearray / memoryview /
            # ndarray payloads alike; arr keeps the buffer alive for the
            # duration of the (synchronous) native send
            arr = np.frombuffer(payload, dtype=np.uint8)
            ptr = arr.ctypes.data
        with self._lock:
            h = self._h
        if h is None:
            raise ConnectionError("native connection closed")
        if msg.trace is not None and self._send2 is not None:
            # the (trace_id, span_id) context rides the TRACE_FLAG wire
            # block exactly as the Python transport emits it, so server
            # child spans join worker spans over the native client too
            rc = self._send2(
                h, int(msg.op), msg.seq, msg.key, msg.cmd, msg.version,
                msg.flags, ptr, n, msg.trace[0], msg.trace[1],
            )
        else:
            rc = self._lib.bpsc_send(
                h, int(msg.op), msg.seq, msg.key, msg.cmd, msg.version,
                msg.flags, ptr, n,
            )
        if rc != 0:
            raise ConnectionError("server connection lost (native send)")

    def pop_cb(self, seq: int):
        with self._lock:
            entry = self._cbs.pop(seq, None)
        return entry[0] if entry is not None else None

    def close_all(self) -> None:
        if self._hist_provider is not None:
            # fold the lanes' final latency totals into the registry
            # WHILE the handle still resolves (bpsc_close erases it)
            from byteps_tpu.core.telemetry import metrics

            metrics().absorb_hist_provider(self._hist_provider)
            self._hist_provider = None
        with self._lock:
            h, self._h = self._h, None
        if h is not None:
            # joins the native lanes; their drain fires pending callbacks
            # (cb(None)) before the join returns
            self._lib.bpsc_close(h)
        with self._lock:
            self.dead = True


class PSClient:
    # class-level defaults for the elastic resharding surface: stub
    # clients (tests build them with ``__new__``) and pre-resharding
    # pickles route legacy without tripping AttributeError
    reshard = False
    map_epoch = 0
    _ownership = None
    _routing: tuple = ((), (), None)
    _max_chases = 8
    #: highest scheduler incarnation seen in a book (zombie fence;
    #: docs/robustness.md "Control-plane recovery")
    sched_incarnation = 0
    _sched_reconnecting = False
    _sched_terminal = False
    _seen_map_epoch = 0
    _seen_ring_overrides: dict = {}
    _reconnect_token = 0
    #: adaptive control plane (docs/autotune.md): the newest adopted
    #: ``tuning`` section + its epoch; class-level defaults keep
    #: __new__-built test stubs and pre-tuner pickles safe
    tuning: Optional[dict] = None
    _tuning_epoch = 0
    _tuning_listeners: tuple = ()

    def __init__(self, cfg: Config, node_uid: Optional[str] = None) -> None:
        self.cfg = cfg
        from byteps_tpu.common.config import resolve_node_uid

        self.node_uid = resolve_node_uid(node_uid)
        self.rank: Optional[int] = None
        self.num_workers = cfg.num_worker
        self.num_servers = cfg.num_server
        self._sched: Optional[socket.socket] = None
        self._sched_lock = threading.Lock()
        self._sched_cbs: Dict[int, threading.Event] = {}
        self._sched_cb_lock = threading.Lock()
        self._sched_seq = 0
        self._sched_dead = False  # set when the scheduler recv loop exits
        # --- control-plane recovery (docs/robustness.md) ---
        # scheduler-link loss no longer latches this node dead: the recv
        # loop's exit hands off to a reconnect state machine that redials
        # the scheduler address with bounded backoff and re-REGISTERs
        # (uid + last-known rank + epochs), while the DATA plane keeps
        # training on the last-adopted book — control_plane_degraded
        # mode.  _sched_up is set while the link is healthy; _sched_
        # terminal marks a reconnect give-up (the legacy latch) so
        # waiters (barrier retries) fail instead of parking forever.
        self.sched_incarnation = 0
        self._sched_up = threading.Event()
        self._sched_terminal = False
        self._sched_reconnecting = False
        #: ownership generation of the ACTIVE reconnect machine: under
        #: repeated link chaos a machine's cleanup can race the next
        #: machine spawned by the recv loop it itself started — only the
        #: holder of the current token may clear flags or latch terminal
        self._reconnect_token = 0
        self._seen_map_epoch = 0
        self._seen_ring_overrides = {}
        self._servers: List[_ServerConn] = []
        self._server_addrs: List[tuple] = []
        #: bumped whenever the server list is rebuilt (elastic server
        #: resize): the engine re-runs each key's init-push barrier — and
        #: re-ships compressor configs — against the new owners before the
        #: key's next use
        self.server_generation = 0
        self._stop = threading.Event()
        self._rebuild_lock = threading.Lock()  # serializes live server swaps
        self._book_token = 0     # RESIZE_SEQ arrival counter (sched thread)
        self._applied_token = 0  # newest book actually applied
        self.is_recovery = False
        #: responses whose payloads landed directly in caller buffers
        self.zero_copy_pulls = 0
        #: newest membership epoch seen in a scheduler book (eviction /
        #: adoption / resize broadcasts bump it; docs/robustness.md)
        self.membership_epoch = 0
        # --- elastic resharding (docs/robustness.md "migration flow") ---
        # ownership = epoch-stamped consistent-hash ring over server
        # RANKS, adopted from books atomically with the connection list
        # (one _routing snapshot: a key routes against the count/list/map
        # it was hashed under, never a mixed pair).  A reply of
        # Op.WRONG_OWNER means the server knows a newer map: the RPC
        # waits (bounded) for its book, re-routes, and resends — the
        # chase; journal replay and init retries chase the same way.
        self.reshard = cfg.elastic_reshard
        #: newest adopted ownership-map epoch; _map_cv is notified on
        #: every adoption so redirect chases can wait for their book
        self.map_epoch = 0
        self._map_cv = threading.Condition()
        self._ownership = None  # OwnershipMap or None (legacy routing)
        #: (servers, ranks, ownership) swapped as ONE atomic snapshot
        self._routing: tuple = ([], [], None)
        #: WRONG_OWNER chases per RPC before surfacing the error
        self._max_chases = 8
        # --- per-RPC deadline machinery (BYTEPS_RPC_DEADLINE_S) ---
        # token → (conn, expire_at); a scanner thread tears down the
        # connection of any RPC that blows its deadline — the drain then
        # fires every pending callback with None and the retry layer takes
        # over.  Lazy: the thread starts on the first armed deadline.
        #
        # The same thread doubles as the retry TIMER WHEEL: backoff-delayed
        # resend callbacks park in a heap and FIRE from the scanner loop,
        # replacing one short-lived threading.Timer thread per retry (at
        # chaos-test retry rates that churn was hundreds of thread spawns
        # per second).  Due callbacks EXECUTE on a small persistent
        # executor pool (bps-rpc-retry-*, grown on backlog to a fixed
        # cap), never the scanner itself: a resend can block — revival
        # dial, or send_msg into the full socket buffer of a hung server —
        # and the ONLY thing that unblocks a wedged send is the scanner
        # expiring that connection's deadline and tearing it down, so the
        # scanner must never be the thread doing the sending.  Bounded
        # thread count, zero per-retry churn.
        self._rpc_tokens = itertools.count()
        self._outstanding: Dict[int, tuple] = {}
        self._outstanding_lock = threading.Lock()
        self._scan_cv = threading.Condition(self._outstanding_lock)
        self._timers: list = []  # heap of (fire_at, tiebreak, fn)
        self._deadline_thread: Optional[threading.Thread] = None
        import queue as _queue

        self._retry_q: "_queue.Queue" = _queue.Queue()
        # executor POOL, grown lazily to a small cap: resends serialize
        # per thread, and one resend can block in a revival dial to a
        # black-holed server — a healthy server's 0.1s-backoff retry must
        # not queue behind it for the dial timeout.  Threads persist
        # (zero per-retry churn); the cap bounds the footprint.
        self._retry_threads: List[threading.Thread] = []
        self._retry_pool_cap = 4
        # --- recovery plane (docs/robustness.md "healing flow") ---
        # per-server heal serialization: concurrent give-ups against one
        # server collapse into a single resync (the generation counter
        # lets late arrivals ride a heal that completed while they waited)
        self._heal_meta_lock = threading.Lock()
        self._heal_locks: Dict[str, threading.Lock] = {}
        self._heal_gen: Dict[str, int] = {}
        # init-idempotency tokens: per-key init sequence, salted per
        # client instance so a restarted process (or a post-shutdown
        # re-init) can never collide with a previous generation's
        # completed-barrier record on the server
        import random as _random

        self._init_seq_lock = threading.Lock()
        self._init_seqs: Dict[int, int] = {}
        self._init_salt = _random.SystemRandom().getrandbits(16)
        # --- adaptive control plane (docs/autotune.md) ---
        # listeners (the engine) run on every NEWER tuning adoption;
        # registration replays the current section so an engine built
        # after connect() (the normal init order) still sees it
        self.tuning = None
        self._tuning_epoch = 0
        self._tuning_listeners: list = []

    # --- rendezvous ------------------------------------------------------

    def connect(self) -> None:
        """Register with the scheduler and connect to every server
        (GetOrInitPS, global.cc:283-297)."""
        from byteps_tpu.comm.transport import connect_control

        self._sched = connect_control(
            self.cfg.ps_root_uri, self.cfg.ps_root_port
        )
        send_message(
            self._sched,
            Message(
                Op.REGISTER,
                payload=json.dumps(
                    {
                        "role": "worker",
                        "host": "",
                        "port": 0,
                        "uid": self.node_uid,
                        # a re-register after resume(num_workers=±k) carries
                        # the NEW expected topology — the scheduler adopts it
                        # (elastic world-size change, operations.cc:96-119)
                        "num_workers": self.cfg.num_worker,
                        "num_servers": self.cfg.num_server,
                        # multi-tenant identity + QoS (docs/async.md): the
                        # scheduler builds the per-job membership map and
                        # the servers' service weights / admission quotas
                        # from these
                        "job": self.cfg.job_id,
                        "job_priority": self.cfg.job_priority,
                        "job_quota_mbps": self.cfg.job_quota_mbps,
                    }
                ).encode(),
            ),
        )
        resp = recv_message(self._sched)
        if resp.status != 0:
            err = json.loads(resp.payload.decode()).get("error", "register refused")
            raise RuntimeError(f"scheduler refused registration: {err}")
        book = json.loads(resp.payload.decode())
        self.rank = book["rank"]
        self.num_workers = self._book_num_workers(book)
        self.num_servers = book["num_servers"]
        self.is_recovery = book.get("is_recovery", False)
        self._fence_book(book)  # learn the scheduler's incarnation
        self._note_membership(book)
        self._sched_up.set()
        # the degraded-state gauge exists from bring-up so bps_top can
        # count healthy (0) vs degraded (1) nodes in the aggregate
        metrics().gauge_set("control_plane_degraded", 0)
        self._server_addrs = [tuple(s) for s in book["servers"]]
        for host, port in self._server_addrs:
            sc = self._new_conn(host, port)
            sc.server_label = str(len(self._servers))
            self._servers.append(sc)
        self._install_routing(
            self._servers, book.get("server_ranks"),
            self._ownership_from_book(book),
        )
        # scheduler receiver for barrier responses
        t = threading.Thread(target=self._sched_recv_loop, daemon=True)
        t.start()
        # periodic heartbeat to the scheduler (ps-lite heartbeat parity;
        # knob: BYTEPS_HEARTBEAT_INTERVAL via Config)
        if self.cfg.heartbeat_interval > 0:
            threading.Thread(
                target=self._heartbeat_loop,
                args=(self.cfg.heartbeat_interval,),
                daemon=True,
            ).start()
        # global barrier mirrors Postoffice::Barrier at init
        # (global.cc:289-294).  On elastic rejoin the scheduler releases
        # the recovering node's barrier immediately (the rest of the
        # cluster is mid-training, not waiting at a barrier).
        self.barrier(GROUP_ALL)

    def close(self) -> None:
        self._stop.set()
        with self._outstanding_lock:
            # wake the deadline/timer scanner so it exits (and drains any
            # parked retry timers through their stop-check fail path)
            self._scan_cv.notify_all()
        for sc in self._servers:
            sc.close_all()
        close_socket(self._sched)
        self._servers = []

    def _sched_request(self, msg: Message,
                       timeout: Optional[float] = None) -> Message:
        """Send a scheduler request and wait for its seq-matched response.
        Raises ConnectionError if the scheduler link is dead or dies while
        waiting — or, with ``timeout``, when no response arrives in time
        (a chaos-dropped control frame would otherwise park the caller
        forever on a healthy connection; heartbeats pass one)."""
        with self._sched_cb_lock:
            if self._sched_dead:
                raise ConnectionError("scheduler connection lost")
            seq = self._sched_seq
            self._sched_seq += 1
            ev = threading.Event()
            box: list = []
            self._sched_cbs[seq] = (ev, box)
        msg.seq = seq
        send_message(self._sched, msg, self._sched_lock)
        if not ev.wait(timeout):
            with self._sched_cb_lock:
                self._sched_cbs.pop(seq, None)
            raise ConnectionError("scheduler request timed out")
        if not box:
            raise ConnectionError("scheduler connection lost")
        return box[0]

    def _fence_book(self, book: dict) -> bool:
        """Incarnation fence (docs/robustness.md "Control-plane
        recovery"): refuse a book stamped with an OLDER scheduler
        incarnation than one this node already acted on — a zombie
        scheduler racing its restarted successor must not roll the
        topology back (the control-plane twin of the zombie-worker
        fence).  Adopts a newer incarnation on accept.  Books without
        the stamp (older schedulers) always pass."""
        inc = int(book.get("sched_incarnation", 0) or 0)
        if inc and self.sched_incarnation and inc < self.sched_incarnation:
            counters().bump("sched_stale_book")
            return False
        if inc > self.sched_incarnation:
            if self.sched_incarnation:
                # scheduler REBIRTH: the successor's tuner numbering
                # restarts, so the monotone adoption fence must re-arm
                # or its decisions would be refused while the dead
                # incarnation's tuning stayed live forever.  -1 (not 0)
                # so even an epoch-0 initial section adopts.  The
                # successor normally RE-ADOPTS the fleet's live state
                # from the survivors' rejoin reports (_tuning_report →
                # AutoTuner.adopt_rejoin_report), so its first book
                # confirms the running decisions; only a tunerless
                # successor (BYTEPS_AUTOTUNE off) ships an empty
                # section, deliberately reverting the fleet to launch
                # values.
                self._tuning_epoch = -1
            self.sched_incarnation = inc
        return True

    def _note_membership(self, book: dict) -> None:
        """Track the scheduler's membership epoch + cumulative eviction
        totals from an address book (observability; docs/robustness.md)."""
        epoch = book.get("epoch")
        if epoch is not None and epoch > self.membership_epoch:
            self.membership_epoch = epoch
        # newest map epoch SEEN in any book — tracked independently of
        # the resharding feature (which only adopts maps when on), so a
        # rejoin re-REGISTER always reports what this node observed and
        # a reborn scheduler fences above it
        me = book.get("map_epoch")
        if me is not None and int(me) >= self._seen_map_epoch:
            self._seen_map_epoch = int(me)
            # newest placement overrides seen in any book: they ride the
            # rejoin report (_tuning_report) so a reborn scheduler can
            # re-adopt placement instead of migrating every overridden
            # key home on its first book
            self._seen_ring_overrides = dict(
                book.get("ring_overrides") or {}
            )
        ev = book.get("evictions") or {}
        for role, name in (("worker", "worker_evicted"),
                           ("server", "server_evicted")):
            if ev.get(role):
                counters().set_floor(name, int(ev[role]))
        self._adopt_tuning(book)

    def _adopt_tuning(self, book: dict) -> None:
        """Adopt a book's ``tuning`` section (docs/autotune.md) when it
        is NEWER than the one already applied — monotone by tuning
        epoch, so a re-broadcast or a racing stale book can never roll
        a fleet decision back.  Listeners (the engine's _apply_tuning)
        run outside any routing lock; a listener error must never
        poison book adoption."""
        t = book.get("tuning")
        if not isinstance(t, dict):
            if self.tuning is not None:
                # the control plane no longer runs a tuner (toggled off,
                # or a reborn scheduler without BYTEPS_AUTOTUNE): revert
                # to legacy — an empty section makes the engine restore
                # its launch fusion threshold and re-enable fleet-
                # disabled codecs.  Once, not per book.
                self.tuning = None
                self._tuning_epoch = 0
                for cb in tuple(self._tuning_listeners):
                    try:
                        cb({})
                    except Exception as e:  # noqa: BLE001
                        from byteps_tpu.common import logging as bpslog

                        bpslog.warning("tuning listener failed: %r", e)
            return
        try:
            epoch = int(t.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            return
        if self.tuning is not None and epoch <= self._tuning_epoch:
            return
        self._tuning_epoch = epoch
        self.tuning = dict(t)
        for cb in tuple(self._tuning_listeners):
            try:
                cb(self.tuning)
            except Exception as e:  # noqa: BLE001
                from byteps_tpu.common import logging as bpslog

                bpslog.warning("tuning listener failed: %r", e)

    def _tuning_report(self) -> Optional[dict]:
        """The fleet-tuning state this node last adopted — the rejoin
        REGISTER carries it so a RESTARTED scheduler's tuner re-adopts
        the live decisions (docs/autotune.md "Rollback flow") instead
        of reverting them with its empty epoch-0 state.  None when no
        tuner ever armed (the report field stays absent and the legacy
        wire is byte-identical)."""
        if self.tuning is None:
            return None
        rep = dict(self.tuning)
        if self._seen_ring_overrides:
            rep["ring_overrides"] = dict(self._seen_ring_overrides)
        return rep

    def add_tuning_listener(self, cb) -> None:
        """Register a fleet-tuning consumer; replays the current
        section immediately (the initial book lands in connect(),
        BEFORE the engine exists to listen)."""
        if not isinstance(self._tuning_listeners, list):
            self._tuning_listeners = []  # stub built via __new__
        self._tuning_listeners.append(cb)
        if self.tuning is not None:
            try:
                cb(self.tuning)
            except Exception as e:  # noqa: BLE001
                from byteps_tpu.common import logging as bpslog

                bpslog.warning("tuning listener failed: %r", e)

    def _book_num_workers(self, book: dict) -> int:
        """The worker count THIS client aggregates over.  Multi-tenant
        books (docs/async.md) carry a per-job membership map — a tenant
        job's rounds involve only ITS workers, so averaging and the
        round-completion expectation use the job's population, not the
        fleet's.  Single-tenant books (no ``jobs`` field, or job 0 not
        split out) fall back to the fleet total, the pre-tenancy
        behavior."""
        jobs = book.get("jobs")
        if jobs:
            # job 0 included: in a MIXED fleet (tenant workers present)
            # the default-namespace job's rounds also complete against
            # only ITS workers, so averaging over the fleet total would
            # divide by the wrong population.  Single-job books yield
            # len == num_workers, the pre-tenancy value.
            mine = jobs.get(str(self.cfg.job_id))
            if mine and mine.get("workers"):
                return len(mine["workers"])
        return book["num_workers"]

    def _ownership_from_book(self, book: Optional[dict]):
        """Build the book's OwnershipMap, or None (resharding off, or an
        older scheduler whose books carry no map)."""
        if not self.reshard or not book:
            return None
        ranks = book.get("server_ranks")
        epoch = book.get("map_epoch")
        if not ranks or epoch is None:
            return None
        from byteps_tpu.common.hashing import OwnershipMap

        return OwnershipMap(
            ranks, epoch=int(epoch), vnodes=self.cfg.ring_vnodes,
            # autotuner hot-key rebalance (docs/autotune.md): per-key
            # placement overrides ride beside the map epoch as one
            # versioned placement
            overrides=book.get("ring_overrides"),
        )

    def _install_routing(self, servers, ranks, omap) -> None:
        """Swap the (connections, ranks, ownership) routing snapshot as
        one atomic reference, and wake redirect chases waiting for the
        map epoch the new book carries."""
        self._routing = (servers, list(ranks or []), omap)
        with self._map_cv:
            self._ownership = omap
            if omap is not None and omap.epoch > self.map_epoch:
                self.map_epoch = omap.epoch
            self._map_cv.notify_all()

    def _wait_map_epoch(self, epoch: int, timeout: float) -> bool:
        """Block until this client's adopted map epoch reaches ``epoch``
        (the epoch a WRONG_OWNER redirect carried) or ``timeout`` —
        chasing before the book lands would just re-route with the same
        stale map."""
        with self._map_cv:
            return self._map_cv.wait_for(
                lambda: self.map_epoch >= epoch or self._stop.is_set(),
                timeout,
            )

    def request_resize(self, num_workers: Optional[int] = None,
                       num_servers: Optional[int] = None) -> dict:
        """Ask the scheduler to adopt a new expected topology from THIS
        live worker — the wire shape of elastic ``resume(num_servers=±k)``
        (a re-REGISTER carrying the new expectation) without tearing the
        runtime down.  Blocks until the scheduler can answer (a scale-up
        reply parks until the new server registers), adopts the returned
        book, and returns it.  With BYTEPS_ELASTIC_RESHARD the resize is
        a live migration: servers ship re-homed keys to the new owners
        and no re-init barrier fires (docs/robustness.md "migration
        flow")."""
        payload = json.dumps({
            "role": "worker", "host": "", "port": 0, "uid": self.node_uid,
            "num_workers": int(num_workers or self.num_workers),
            "num_servers": int(num_servers or self.num_servers),
        }).encode()
        resp = self._sched_request(Message(Op.REGISTER, payload=payload))
        if resp.status != 0:
            err = json.loads(resp.payload.decode()).get("error", "refused")
            raise RuntimeError(f"scheduler refused resize: {err}")
        book = json.loads(resp.payload.decode())
        if not self._fence_book(book):
            raise ConnectionError("resize book from a stale scheduler incarnation")
        self.num_workers = self._book_num_workers(book)
        self._note_membership(book)
        with self._sched_cb_lock:
            self._book_token += 1
            token = self._book_token
        self._rebuild_servers(
            book["num_servers"], [tuple(s) for s in book["servers"]],
            token, book=book,
        )
        return book

    def barrier(self, group: int = GROUP_WORKERS) -> None:
        """Scheduler barrier.  Rides through a scheduler crash: a wait
        broken by link loss re-arms against the successor once the
        reconnect machine rejoins (the restarted scheduler's barrier
        table starts empty, and every surviving participant re-sends, so
        pairing stays correct).  Raises ConnectionError only once the
        reconnect machine has terminally given up."""
        while True:
            try:
                self._sched_request(Message(Op.BARRIER, flags=group))
                return
            except ConnectionError:
                if self._stop.is_set() or not self._await_control_plane():
                    raise

    def _await_control_plane(self, poll: float = 0.25) -> bool:
        """Block until the scheduler link is healthy again (True) or the
        reconnect machine gave up / the client closed (False).  The wait
        is bounded by the reconnect machine itself: it either rejoins or
        sets the terminal latch within its retry budget."""
        while not self._stop.is_set():
            if self._sched_up.wait(poll):
                return True
            with self._sched_cb_lock:
                if self._sched_terminal and not self._sched_reconnecting:
                    return False
        return False

    def query_cluster(self) -> dict:
        """Heartbeat ages per node from the scheduler (failure detection,
        SURVEY §5.3)."""
        from byteps_tpu.comm.transport import decode_liveness

        return decode_liveness(self._sched_request(Message(Op.QUERY)).payload)

    def _heartbeat_loop(self, interval: float) -> None:
        beat_incarnation = None
        while not self._stop.is_set():
            if self._stop.wait(interval):
                return
            with self._sched_cb_lock:
                if self._sched_dead:
                    # control_plane_degraded: the reconnect machine owns
                    # the link — keep ticking (a single send failure must
                    # never permanently end all future beats; the fix for
                    # the terminal-return latch, docs/robustness.md)
                    continue
            inc = self.sched_incarnation
            if inc != beat_incarnation:
                # first beat to a NEW scheduler incarnation ships the
                # FULL metric history, not a delta against baselines the
                # dead scheduler took to its grave — the successor's
                # aggregate starts empty.  reship_for is idempotent per
                # incarnation (in-process fleets share one registry).
                metrics().reship_for(inc)
                beat_incarnation = inc
            # piggyback this process's metric DELTAS on the beat: the
            # scheduler folds them into its cluster-wide aggregate
            # registry (served on its own BYTEPS_METRICS_PORT), so one
            # scrape of the scheduler sees the whole job without the
            # scraper having to discover every worker's endpoint
            delta = metrics().delta_snapshot()
            # flight-recorder ledger tail (docs/observability.md "Flight
            # recorder & doctor"): a compact window of recent per-step
            # records rides every beat so the scheduler holds a
            # cluster-wide step matrix.  Idempotent — the window is
            # re-shipped and the scheduler dedupes by step index, so a
            # lost beat costs nothing and the requeue path (which only
            # folds metric increments) never needs to know about it.
            from byteps_tpu.core.flightrec import get_process_recorder

            rec = get_process_recorder()
            ups = None
            if rec is not None and rec.enabled:
                tail = rec.ledger_tail()
                if tail:
                    delta["fr"] = tail
                # fleet-central bundle upload (BYTEPS_FLIGHT_UPLOAD):
                # compact trigger bundles ride the beat to the
                # scheduler's BYTEPS_FLIGHT_DIR.  Taken (not re-shipped
                # like the tail) — a failed beat gives them back below.
                ups = rec.take_uploads()
                if ups:
                    delta["fb"] = ups
            try:
                payload = json.dumps(delta).encode() if delta else b""
                # bounded wait: a chaos-dropped PING on a healthy link
                # must cost one beat, not park this thread forever
                self._sched_request(
                    Message(Op.PING, payload=payload),
                    timeout=max(2.0, 4 * interval),
                )
            except (ConnectionError, OSError):
                # the delta was consumed from the shipped baselines but
                # may never have been delivered — give it back for the
                # next beat (or a successor control plane).  Delivery
                # toward the aggregate is AT-LEAST-ONCE by design
                # (docs/observability.md): a timed-out beat whose
                # request actually landed re-ships its increments, a
                # deliberate over-count bias — losing increments would
                # silently understate degradation, which is worse.
                metrics().requeue_delta(delta)
                if ups and rec is not None:
                    rec.requeue_uploads(ups)
                continue

    def _sched_recv_loop(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_message(self._sched)
                except (ConnectionError, OSError):
                    return
                if msg.op == Op.ADDRBOOK and msg.seq == RESIZE_SEQ:
                    # another worker resized the cluster: adopt the worker
                    # count (averaging reads it live) and, on a SERVER
                    # resize, rebuild the connection set — key→server
                    # routing follows num_servers automatically and the
                    # engine re-inits keys on their new owners
                    # (server_generation bump)
                    book = json.loads(msg.payload.decode())
                    if not self._fence_book(book):
                        # zombie scheduler racing its restarted
                        # successor: refuse the stale-incarnation book
                        continue
                    self.num_workers = self._book_num_workers(book)
                    self._note_membership(book)
                    new_addrs = [tuple(s) for s in book["servers"]]
                    # token = book arrival order on THIS (single) thread:
                    # rebuild threads acquire the lock in arbitrary order,
                    # so staleness is decided by token, not address
                    # equality.  EVERY book spawns a rebuild — even one
                    # matching the live set (a rollback can race a failed
                    # rebuild's delayed retry; the no-op case is detected
                    # under the rebuild lock, where it is atomic with any
                    # in-flight apply).  Rebuild OFF this thread: connects
                    # can block/fail and must neither stall scheduler
                    # callback delivery nor kill this loop (→ _sched_dead)
                    with self._sched_cb_lock:
                        self._book_token += 1
                        token = self._book_token
                    threading.Thread(
                        target=self._rebuild_servers,
                        args=(book["num_servers"], new_addrs, token),
                        kwargs={"book": book},
                        daemon=True,
                    ).start()
                    continue
                with self._sched_cb_lock:
                    entry = self._sched_cbs.pop(msg.seq, None)
                if entry is not None:
                    ev, box = entry
                    box.append(msg)
                    ev.set()
        finally:
            # wake every pending waiter with an empty box → they raise
            # ConnectionError instead of hanging on a dead scheduler; flag
            # the link dead so LATER _sched_request calls fail fast instead
            # of registering callbacks nobody will ever drain
            with self._sched_cb_lock:
                self._sched_dead = True
                self._sched_up.clear()
                pending = list(self._sched_cbs.values())
                self._sched_cbs.clear()
                spawn_reconnect = (
                    not self._stop.is_set()
                    and not self._sched_reconnecting
                )
                latch_terminal = False
                token = 0
                if spawn_reconnect:
                    if self.cfg.sched_reconnect_retries > 0:
                        self._sched_reconnecting = True
                        self._reconnect_token += 1
                        token = self._reconnect_token
                    else:
                        # legacy terminal latch (BYTEPS_SCHED_RECONNECT_
                        # RETRIES=0): degraded forever, waiters fail fast
                        self._sched_terminal = True
                        latch_terminal = True
                        spawn_reconnect = False
            for ev, _ in pending:
                ev.set()
            if latch_terminal:
                # the gauge must still report the outage even though no
                # reconnect machine will run
                metrics().gauge_set("control_plane_degraded", 1)
            if spawn_reconnect:
                # hand off to the reconnect state machine instead of
                # latching dead: the data plane keeps training on the
                # last-adopted book while this node redials the
                # scheduler address (control_plane_degraded mode,
                # docs/robustness.md "Control-plane recovery")
                metrics().gauge_set("control_plane_degraded", 1)
                threading.Thread(
                    target=self._sched_reconnect_loop, args=(token,),
                    name="bps-sched-reconnect", daemon=True,
                ).start()

    # --- control-plane reconnect state machine ---------------------------
    #
    # docs/robustness.md "Control-plane recovery".  Scheduler-link loss
    # used to latch `_sched_dead` terminally: one `kill -9` of the
    # scheduler and the job could never resize, evict, reshard, or
    # aggregate metrics again — even though the worker↔server data plane
    # was perfectly healthy.  Instead the node enters control_plane_
    # degraded mode (data plane trains on the last-adopted book) while
    # this machine redials the scheduler address with bounded backoff
    # and re-REGISTERs carrying its uid, last-known rank, and the
    # membership/map epochs it acted under — a restarted scheduler
    # rebuilds its registration table from exactly these reports.

    def _sched_reconnect_loop(self, token: int = 0) -> None:
        from byteps_tpu.comm.retry import Backoff

        from byteps_tpu.common import logging as bpslog

        backoff = Backoff(
            base=max(0.05, self.cfg.sched_reconnect_backoff_s), cap=10.0
        )
        attempts = 0
        try:
            while not self._stop.is_set():
                if attempts >= self.cfg.sched_reconnect_retries:
                    bpslog.warning(
                        "scheduler reconnect gave up after %d attempts — "
                        "control plane is down for good (data plane "
                        "continues on the last book)", attempts,
                    )
                    with self._sched_cb_lock:
                        if self._reconnect_token == token:
                            self._sched_terminal = True
                    return
                attempts += 1
                counters().bump("sched_reconnect")
                sock = None
                try:
                    sock, book = self._sched_re_register()
                except (ConnectionError, OSError, RuntimeError, ValueError):
                    if sock is not None:
                        close_socket(sock)
                    if self._stop.wait(backoff.next_delay()):
                        return
                    continue
                if book is None:
                    # register answered by a STALE incarnation (zombie
                    # scheduler still bound to the address): refuse and
                    # redial — the successor will win the port
                    close_socket(sock)
                    if self._stop.wait(backoff.next_delay()):
                        return
                    continue
                self._adopt_rejoin(sock, book)
                return
        finally:
            latch = False
            with self._sched_cb_lock:
                if self._reconnect_token == token and self._sched_reconnecting:
                    # loop exiting WITHOUT a successful adopt (give-up,
                    # stop, or an unexpected error unwinding this
                    # thread): latch terminal so barrier retries fail
                    # instead of polling a machine that no longer
                    # exists.  The token gate matters: a successful
                    # _adopt_rejoin hands ownership to the recv loop it
                    # spawns, and if THAT loop already died and spawned
                    # the next machine (token advanced), this exiting
                    # one must not clear the successor's flag or latch
                    # terminal over its live retry budget.
                    self._sched_reconnecting = False
                    if self._sched_dead:
                        self._sched_terminal = True
                        latch = True
            if latch:
                metrics().gauge_set("control_plane_degraded", 1)

    def _sched_re_register(self):
        """One redial + re-REGISTER attempt → (socket, book).  The book
        is None when a zombie (stale-incarnation) scheduler answered.
        Blocks in recv until the scheduler replies — a RESTARTED
        scheduler parks the reply until its population completes or its
        rejoin grace window expires, and this thread is the right place
        to wait that out."""
        from byteps_tpu.comm.transport import connect_control

        sock = connect_control(self.cfg.ps_root_uri, self.cfg.ps_root_port)
        try:
            payload = json.dumps({
                "role": "worker", "host": "", "port": 0,
                "uid": self.node_uid,
                # LIVE topology expectation, not the launch-time config:
                # the cluster may have been resized since
                "num_workers": self.num_workers,
                "num_servers": self.num_servers,
                # state-reconstruction report for a reborn scheduler
                "last_rank": self.rank,
                "epoch": self.membership_epoch,
                "map_epoch": max(self.map_epoch, self._seen_map_epoch),
                # control-plane reconnect, NOT a process restart: the
                # runtime is live and connect()'s re-init barrier will
                # not run, so the scheduler must not arm the
                # recovered-conn barrier bypass for this conn
                "reconnect": True,
                "job": self.cfg.job_id,
                "job_priority": self.cfg.job_priority,
                "job_quota_mbps": self.cfg.job_quota_mbps,
                # last-adopted fleet tuning + placement overrides: a
                # reborn scheduler re-adopts these before its first
                # books (AutoTuner.adopt_rejoin_report) so live
                # decisions survive the restart
                "tuning": self._tuning_report(),
            }).encode()
            send_message(sock, Message(Op.REGISTER, payload=payload))
            resp = recv_message(sock)
            if resp.status != 0:
                err = json.loads(resp.payload.decode()).get(
                    "error", "register refused"
                )
                raise RuntimeError(f"scheduler refused rejoin: {err}")
            book = json.loads(resp.payload.decode())
            if not self._fence_book(book):
                return sock, None
            return sock, book
        except BaseException:
            close_socket(sock)
            raise

    def _adopt_rejoin(self, sock, book: dict) -> None:
        """Install a successful rejoin: swap the control socket in, adopt
        the book (rank is stable — the scheduler honored the uid/rank
        report), restart the receiver, and wake barrier retries."""
        self.rank = book["rank"]
        self.num_workers = self._book_num_workers(book)
        self.is_recovery = True
        self._note_membership(book)
        counters().bump("sched_rejoin")
        with self._sched_cb_lock:
            old, self._sched = self._sched, sock
            self._sched_dead = False
            # hand the NEXT reconnect cycle to the recv loop we are about
            # to spawn: if the rejoined link dies again (likely under
            # scheduler-link chaos), its finally must see reconnecting
            # False and start a fresh machine rather than assume this
            # (exiting) one still owns the link
            self._sched_reconnecting = False
            self._book_token += 1
            token = self._book_token
        close_socket(old)  # the dead link's fd must not outlive the rejoin
        threading.Thread(target=self._sched_recv_loop, daemon=True).start()
        # adopt the book's server set/ownership map like a RESIZE_SEQ
        # broadcast — when nothing changed (the common crash-restart
        # case) this is the no-op path: no reconnect churn, no
        # generation bump, the version sequence continues bitwise
        self._rebuild_servers(
            book["num_servers"], [tuple(s) for s in book["servers"]],
            token, book=book,
        )
        with self._sched_cb_lock:
            # only mark the link up if it is STILL up: under repeated
            # chaos the fresh socket can die during the rebuild above,
            # and re-setting the event then would make barrier retries
            # busy-spin against a dead link until the next rejoin
            alive = not self._sched_dead
            if alive:
                self._sched_up.set()
        if alive:
            metrics().gauge_set("control_plane_degraded", 0)

    def _rebuild_servers(
        self,
        num_servers: int,
        new_addrs: List[tuple],
        token: int = 1 << 62,
        retry_delay: float = 2.0,
        book: Optional[dict] = None,
    ) -> None:
        """Adopt a resized server book live: connect to the new set, swap,
        then fail the old connections' in-flight requests (same path as a
        server death — the handle errors instead of hanging).  Requests
        racing the swap may still land on an old connection and fail; the
        caller's next round routes and re-inits against the new owners.

        Runs on its own thread (a connect may block or fail during elastic
        churn); rebuilds are serialized, and a stale book — one that
        ARRIVED before the currently-applied one, regardless of which
        thread wins the lock — is skipped by its monotonic ``token``."""
        with self._rebuild_lock:
            if token <= self._applied_token or self._stop.is_set():
                return  # superseded by a newer book, or shutting down
            if token < self._book_token:
                # a newer book exists and ITS rebuild was spawned
                # unconditionally — let it establish the truth; applying
                # this older one would override the correct topology
                return
            if new_addrs == self._server_addrs:
                # live set already matches this newest book (rollback
                # racing a failed rebuild's retry): mark applied so older
                # pending retries cancel, no reconnect churn.  The book's
                # ownership map still installs — rank identities can
                # change under identical addresses (dead-slot adoption)
                self.num_servers = num_servers
                omap = self._ownership_from_book(book)
                if omap is not None:
                    self._install_routing(
                        self._servers, (book or {}).get("server_ranks"),
                        omap,
                    )
                self._applied_token = token
                return
            fresh: List[_ServerConn] = []
            for attempt in range(3):
                if token < self._book_token:
                    # superseded mid-rebuild: stop holding the lock through
                    # further connect timeouts; the newer book's rebuild is
                    # blocked on us and owns the truth
                    for sc in fresh:
                        sc.close_all()
                    return
                try:
                    for host, port in new_addrs[len(fresh):]:
                        sc = self._new_conn(host, port)
                        sc.server_label = str(len(fresh))
                        fresh.append(sc)
                    break
                except OSError as e:
                    if attempt == 2:
                        # persistent: keep the current (stale) server set for
                        # now (the control plane stays alive, in-flight
                        # failures surface per-request), but don't stay
                        # desynced forever — RESIZE_SEQ books are broadcast
                        # once, so schedule a delayed re-attempt of this same
                        # book; a newer book supersedes it via the token check
                        from byteps_tpu.common import logging as bpslog

                        bpslog.warning(
                            "server-resize rebuild failed after retries: %r "
                            "— retrying in %.0fs", e, retry_delay
                        )
                        for sc in fresh:
                            sc.close_all()

                        def _retry():
                            if self._stop.wait(retry_delay):
                                return
                            self._rebuild_servers(
                                num_servers, new_addrs, token,
                                min(retry_delay * 2, 30.0), book=book,
                            )

                        threading.Thread(target=_retry, daemon=True).start()
                        return
                    self._stop.wait(0.3 * (attempt + 1))
            if token < self._book_token:
                # a newer book arrived while we were blocked in connects;
                # its unconditionally-spawned rebuild owns the truth
                for sc in fresh:
                    sc.close_all()
                return
            old, self._servers = self._servers, fresh
            self._server_addrs = list(new_addrs)
            self.num_servers = num_servers
            omap = self._ownership_from_book(book)
            if self.reshard:
                self._install_routing(
                    fresh, (book or {}).get("server_ranks"), omap
                )
            else:
                # legacy clients (and __new__-built test stubs) have no
                # map condition variable; keep the snapshot coherent so
                # _conn_for's identity check sees the fresh list
                self._routing = (fresh, [], None)
            if omap is None:
                # legacy resize: keys re-home via the hash fns onto
                # fresh stores — the engine re-runs every key's
                # init-push barrier against the new owners
                self.server_generation += 1
            # else: live resharding — the servers migrate each re-homed
            # key's state (store + ledger + init tokens) to its new
            # owner, so the version sequence continues in place and NO
            # re-init barrier fires (docs/robustness.md "migration flow")
            self._applied_token = token
        for sc in old:
            sc.close_all()  # recv loops exit → mark_dead fails pendings

    def _new_conn(self, host: str, port: int, dial_timeout: float = 30.0):
        """Build a server connection: the C++ data plane when
        BYTEPS_NATIVE_CLIENT=1 and the lib speaks it (tcp/uds only —
        the shm van's Python client is already zero-copy), else the
        Python lanes + recv threads.  ``dial_timeout`` bounds the connect
        (revival dials pass a deadline-scaled bound; the native client
        keeps its own fixed 30s)."""
        from byteps_tpu.comm.shaping import shaping_enabled
        from byteps_tpu.comm.van import CHAOS_PREFIX, SHM_PREFIX

        if shaping_enabled() and self.cfg.native_client:
            from byteps_tpu.comm.shaping import warn_native_bypass_once

            warn_native_bypass_once("ignoring BYTEPS_NATIVE_CLIENT=1")
        elif self.cfg.native_client and not host.startswith(
            (SHM_PREFIX, CHAOS_PREFIX)  # chaos needs the Python fault layer
        ):
            from byteps_tpu.native import get_lib

            lib = get_lib()
            if lib is not None and hasattr(lib, "bpsc_drain"):
                return _NativeServerConn(
                    host, port, streams=self.cfg.tcp_streams,
                    on_zero_copy=self._count_zero_copy,
                )
        sc = _ServerConn(host, port, streams=self.cfg.tcp_streams,
                         dial_timeout=dial_timeout)
        self._start_recv_loops(sc)
        return sc

    def _count_zero_copy(self) -> None:
        self.zero_copy_pulls += 1

    # --- per-RPC deadlines + retry (docs/robustness.md) ------------------

    def _worker_flag(self) -> int:
        """Worker identity for the header ``flags`` byte: rank+1, so the
        server can dedupe replayed pushes on (worker, key, version).  0 =
        no identity (rank unknown, or ≥255 workers — the u8 runs out) and
        the server skips dedupe for that push."""
        r = self.rank
        return r + 1 if r is not None and 0 <= r < 255 else 0

    def _init_token(self, key: int) -> int:
        """Init-idempotency token carried in the INIT frame's ``version``
        field (docs/robustness.md): low 16 bits = this client's per-key
        init sequence, high 16 bits = the membership epoch folded with a
        per-client random salt.  Every RETRY of one logical init reuses
        the same token, so a replayed INIT whose barrier already released
        is acked from the server's completed-barrier record instead of
        re-parked (the dropped-ack strand).  Epoch-scoping + the salt
        make elastic rejoin and post-shutdown re-init mint FRESH tokens,
        so a genuine new barrier always parks."""
        with self._init_seq_lock:
            seq = self._init_seqs.get(key, 0) + 1
            self._init_seqs[key] = seq
        high = (self._init_salt ^ (self.membership_epoch & 0xFFFF)) & 0xFFFF
        return (high << 16) | (seq & 0xFFFF)

    def _ensure_scanner_locked(self) -> None:
        """Start (or wake) the shared deadline/timer scanner thread.
        Caller holds ``_outstanding_lock``."""
        if self._deadline_thread is None:
            self._deadline_thread = threading.Thread(
                target=self._deadline_loop, name="bps-rpc-deadline",
                daemon=True,
            )
            self._deadline_thread.start()
        else:
            self._scan_cv.notify()

    def _deadline_arm(self, sc, sid: Optional[str] = None) -> Optional[int]:
        """Register one in-flight RPC attempt with the deadline scanner;
        returns a token for :meth:`_deadline_clear`, or None when
        deadlines are disabled.  ``sid`` (server-rank string) labels the
        expiry counter so one hung server stands out of the total."""
        if self.cfg.rpc_deadline_s <= 0:
            return None
        token = next(self._rpc_tokens)
        expire = time.monotonic() + self.cfg.rpc_deadline_s
        with self._outstanding_lock:
            self._outstanding[token] = (sc, expire, sid)
            self._ensure_scanner_locked()
        return token

    def _deadline_clear(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._outstanding_lock:
            self._outstanding.pop(token, None)

    def _timer_after(self, delay: float, fn) -> None:
        """Timer wheel: fire ``fn`` after ``delay`` seconds (timed by the
        ``bps-rpc-deadline`` scanner, executed on the bounded
        ``bps-rpc-retry-*`` pool).  Replaces per-retry ``threading.Timer``
        spawning with a handful of persistent threads.  After close(),
        ``fn`` runs inline so its stop-check resolves the caller (fail →
        on_error) instead of parking forever."""
        import heapq

        with self._outstanding_lock:
            if not self._stop.is_set():
                heapq.heappush(
                    self._timers,
                    (time.monotonic() + delay, next(self._rpc_tokens), fn),
                )
                self._ensure_scanner_locked()
                return
        fn()

    def _dispatch_retry(self, fn) -> None:
        """Queue a due retry callback onto the persistent executor pool.
        An executor may block in a resend (revival dial, wedged send);
        the scanner stays free to expire deadlines — including the one
        whose teardown unblocks a wedged send — and a visible backlog
        grows the pool (to the cap) so one blocked dial doesn't
        head-of-line-block other servers' retries."""
        self._retry_q.put(fn)
        threads = self._retry_threads
        if not threads or (
            self._retry_q.qsize() > 0 and len(threads) < self._retry_pool_cap
        ):
            t = threading.Thread(
                target=self._retry_loop,
                name=f"bps-rpc-retry-{len(threads)}", daemon=True,
            )
            threads.append(t)
            t.start()

    def _retry_loop(self) -> None:
        import queue as _queue

        while True:
            try:
                fn = self._retry_q.get(timeout=0.5)
            except _queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                # after close(): still run — fn's stop-check fails it out
                # through on_error instead of stranding its waiter
                fn()
            except Exception:  # noqa: BLE001 — executor must survive
                pass

    def _deadline_loop(self) -> None:
        """Deadline scanner + retry timer wheel (one timing thread).

        Deadlines: an RPC past its deadline means its server is hung (a
        dead one would have closed the connection).  Tear the suspect
        connection down — the recv-loop drain fires every pending callback
        with None, so ALL of that connection's RPCs funnel into the one
        retry path, and no late response can race a retried pull into a
        caller's zero-copy sink (the old lanes are fully dead first).

        Timers: backoff-delayed resends parked by :meth:`_timer_after`
        become DUE here and are handed to the executor thread (see
        :meth:`_dispatch_retry` for why they must not run on this one).
        The condition wait sleeps exactly until the next timer or the
        next deadline scan tick, whichever is sooner, and is notified on
        every new arm/park so an earlier event never waits behind a
        longer sleep."""
        import heapq

        tick = (
            max(0.01, min(0.25, self.cfg.rpc_deadline_s / 4))
            if self.cfg.rpc_deadline_s > 0 else 0.25
        )
        try:
            while True:
                due, doomed = [], []
                with self._outstanding_lock:
                    if self._stop.is_set():
                        return
                    now = time.monotonic()
                    while self._timers and self._timers[0][0] <= now:
                        due.append(heapq.heappop(self._timers)[2])
                    for t in [
                        t for t, (_, at, _sid) in self._outstanding.items()
                        if at <= now
                    ]:
                        sc, _, sid = self._outstanding.pop(t)
                        doomed.append((sc, sid))
                    if not due and not doomed:
                        timeout = (
                            self._timers[0][0] - now if self._timers else None
                        )
                        if self._outstanding:
                            timeout = (
                                tick if timeout is None else min(timeout, tick)
                            )
                        self._scan_cv.wait(timeout)
                        continue
                # teardowns on THIS thread (close_all never blocks), due
                # retries handed to the executor thread (a resend can
                # block — and the teardown side must stay live to unblock
                # it; see __init__)
                if doomed:
                    for sc, sid in doomed:
                        counters().bump(
                            "rpc_deadline_expired",
                            labels={"server": sid} if sid is not None else None,
                        )
                    for sc in {id(s): s for s, _ in doomed}.values():
                        try:
                            sc.close_all()
                        except Exception:  # noqa: BLE001
                            pass
                for fn in due:
                    self._dispatch_retry(fn)
        finally:
            # shutdown drain: every parked retry must still resolve (its
            # stop-check fails it through on_error) — parking it forever
            # would strand a synchronize() waiter
            with self._outstanding_lock:
                leftovers = [fn for _, _, fn in self._timers]
                self._timers.clear()
            for fn in leftovers:
                try:
                    fn()
                except Exception:  # noqa: BLE001
                    pass

    def _async_rpc(
        self,
        make_msg: Callable[[int], Message],
        key: int,
        deliver: Callable[[Message], None],
        on_error: Optional[Callable[[], None]],
        sink: Optional[memoryview] = None,
        abort_check: Optional[Callable[[], bool]] = None,
        precheck: Optional[Callable[[], bool]] = None,
        heal: bool = True,
        chase: bool = True,
    ) -> None:
        """Send one async RPC with deadline + retry + revival.

        ``make_msg(seq)`` builds the wire message per attempt;
        ``deliver(msg)`` fires once on success; ``on_error`` fires once
        when ``BYTEPS_RPC_RETRIES`` attempts are exhausted (or
        immediately with retries disabled — the legacy fail-fast path).

        ``abort_check``: returns True once the caller has abandoned this
        RPC's whole operation (engine job failed) — pending retries stop
        resending and route to ``on_error`` instead (the caller's error
        path is idempotent and still owes per-task cleanup: queue
        accounting, round-gate re-arm).  Without the fence, a retry
        timer armed before the abandonment could replay an
        old-generation push AFTER the re-init barrier cleared the
        server's dedupe ledger, double-summing that worker.

        ``precheck``: evaluated before EVERY attempt (first and retries);
        returning False fails the RPC straight to ``on_error`` without
        sending.  Used by fused frames to bail out the moment the server
        set resizes — a pre-resize pack's members may no longer share a
        destination, and the caller's error path knows how to regroup
        (engine unfuse fallback), while blind resends would just burn the
        retry budget shipping mis-homed keys.

        ``heal``: with retries exhausted, route ONCE through the in-place
        resync heal (docs/robustness.md "healing flow") before surfacing
        the error — the give-up may be one-sided (every frame to a LIVE
        server lost) and a successful server resync + journal replay
        earns the RPC one fresh attempt.  Fused frames pass ``False``:
        their error path is the unfuse fallback, and the per-key unfused
        RPCs it spawns carry their own heal.
        """
        from byteps_tpu.comm.retry import Backoff

        state = {"attempt": 0}
        backoff = Backoff(base=self.cfg.rpc_backoff_s, cap=2.0)
        # server-rank label for the robustness counters: a single sick
        # server must be visible in the per-peer dimension, not just as
        # an anonymous bump of the flat total (docs/observability.md)
        try:
            sid = str(self.server_for(key))
        except (ValueError, ZeroDivisionError, IndexError, ConnectionError):
            sid = "?"

        def aborted_cleanup() -> bool:
            """True (and routes to on_error) when the op is abandoned."""
            if abort_check is not None and abort_check():
                if on_error is not None:
                    on_error()
                return True
            return False

        def finish_fail() -> None:
            counters().bump("rpc_giveup", labels={"server": sid})
            if on_error is not None:
                on_error()

        def fail() -> None:
            # retries exhausted: before surfacing the error, try the
            # in-place heal ONCE — resync the server's authoritative
            # ledger, replay journaled pushes it never absorbed, then
            # re-attempt this RPC (docs/robustness.md "healing flow").
            # Off this thread: the heal blocks in dials and recovery
            # RPCs, and fail() can fire from a recv-loop drain.
            if (heal and not state.get("healed") and not self._stop.is_set()
                    and self.cfg.resync_deadline_s > 0):
                state["healed"] = True

                def heal_and_resend() -> None:
                    if aborted_cleanup():
                        return
                    if self._heal_in_place(key, sid):
                        state["attempt"] = 0
                        send_attempt()
                    else:
                        finish_fail()

                self._dispatch_retry(heal_and_resend)
                return
            finish_fail()

        def retry_later() -> None:
            if aborted_cleanup():
                return  # abandoned: no resend, cleanup via on_error
            if self._stop.is_set() or state["attempt"] >= self.cfg.rpc_retries:
                fail()
                return
            state["attempt"] += 1
            counters().bump("rpc_retry", labels={"server": sid})
            # timer wheel, not threading.Timer: no per-retry thread churn
            self._timer_after(backoff.next_delay(), send_attempt)

        def chase_redirect(msg: Message) -> None:
            # Op.WRONG_OWNER: the server holds a NEWER ownership map —
            # this key migrated (docs/robustness.md "migration flow").
            # Wait (bounded) for the book that map rode in on, then
            # resend: routing re-runs per attempt, so the resend lands on
            # the new owner, whose migrated per-(worker, key) ledger
            # dedupes anything the old owner already summed.  A chase
            # does not consume the retry budget (the server answered;
            # nothing failed) but is capped so a pathological ping-pong
            # still surfaces an error instead of looping forever.
            counters().bump("wrong_owner_redirect", labels={"server": sid})
            if aborted_cleanup():
                return
            if not chase:
                # fused frames never chase: the new map may scatter the
                # pack's members across servers, so resending the intact
                # frame just ping-pongs — the caller's error path (engine
                # unfuse fallback) regroups into per-key RPCs that each
                # chase on their own
                fail()
                return
            state["chases"] = state.get("chases", 0) + 1
            if self._stop.is_set() or state["chases"] > self._max_chases:
                fail()
                return
            target = msg.version

            def rechase() -> None:
                if aborted_cleanup():
                    return
                self._wait_map_epoch(
                    target, timeout=min(2.0, 0.25 * state["chases"])
                )
                send_attempt()

            # off the recv thread: the map-epoch wait blocks
            self._dispatch_retry(rechase)

        def send_attempt() -> None:
            if aborted_cleanup():
                return
            if self._stop.is_set() or (
                precheck is not None and not precheck()
            ):
                fail()
                return
            try:
                sc = self._conn_for(key, revive=state["attempt"] > 0)
            except (ConnectionError, OSError):
                retry_later()
                return
            token_box: list = [None]
            t_sent = time.monotonic()

            def on_reply(msg: Optional[Message]) -> None:
                self._deadline_clear(token_box[0])
                if msg is None:
                    retry_later()
                elif msg.op == Op.WRONG_OWNER:
                    chase_redirect(msg)
                elif aborted_cleanup():
                    pass  # late success on an abandoned op: cleanup only
                else:
                    # per-ATTEMPT round trip (retries each time their own
                    # attempt; the retry cost itself shows up in
                    # retry_backoff_seconds + the rpc_retry counter).
                    # Labeled per server RANK like the rpc_* counters:
                    # the flight recorder's straggler rule needs "whose
                    # p99 ran away THIS step", which a flat family can
                    # never answer (docs/observability.md)
                    rpc_labels = {"server": sid}
                    if self.cfg.job_id:
                        # per-tenant slice (docs/async.md); job 0 keeps
                        # the pre-tenancy series shape
                        rpc_labels["job"] = str(self.cfg.job_id)
                    metrics().observe(
                        "rpc_round_trip_seconds", time.monotonic() - t_sent,
                        labels=rpc_labels,
                    )
                    deliver(msg)

            # arm BEFORE alloc: alloc_seq on a dead connection fires
            # on_reply(None) synchronously, which must find the token
            token_box[0] = self._deadline_arm(sc, sid)
            seq = sc.alloc_seq(on_reply, sink=sink)
            if seq < 0:
                return  # on_reply(None) already fired → retry scheduled
            try:
                sc.send_msg(make_msg(seq))
                # every frame that actually hit the wire (incl. retries) —
                # the denominator tools/fusion_bench.py compares
                counters().bump("wire_rpc")
            except (ConnectionError, OSError):
                # died between alloc and send: claim the callback — if the
                # drain beat us to it, on_reply(None) already retried
                if sc.pop_cb(seq) is not None:
                    self._deadline_clear(token_box[0])
                    retry_later()

        send_attempt()

    # --- recovery plane: in-place heal via server-driven resync ----------
    #
    # docs/robustness.md "healing flow".  A worker that exhausted its RPC
    # retries against a LIVE server (one-sided degradation: chaos drops,
    # a flapping link, a deadline storm) used to have only the global
    # re-init barrier — which waits for peers that never come, stranding
    # the whole job.  Instead: ask the server for its authoritative
    # per-key round/ledger state (Op.RESYNC_QUERY), replay exactly the
    # journaled pushes it never absorbed, and resume in place.  Peers
    # never block, no barrier, no scheduler involvement.

    def resync_in_place(self, key: int) -> bool:
        """Public entry to the heal state machine (engine / api layer):
        resync ``key``'s owning server and replay whatever journaled
        rounds it is missing.  True = the server's ledger now agrees
        with this worker's emission history."""
        try:
            sid = str(self.server_for(key))
        except (ValueError, ZeroDivisionError, IndexError, ConnectionError):
            return False
        return self._heal_in_place(key, sid)

    def _heal_in_place(self, key: int, sid: str) -> bool:
        """One heal attempt, serialized per server: query → replay →
        resume, bounded by ``BYTEPS_RESYNC_DEADLINE_S`` wall-clock.
        Counters: ``resync_attempt`` / ``resync_replayed_rounds`` /
        ``resync_giveup`` (flat + per-server labels); the attempt also
        lands as a ``RESYNC`` span on the process timeline, and the wire
        query carries its trace context so the server's ``resync`` child
        span joins it on the merged Perfetto view."""
        if (self.cfg.resync_deadline_s <= 0 or self._stop.is_set()
                or not self._worker_flag()):
            # anonymous workers (no rank identity) have no ledger slot on
            # the server — there is nothing to resync against
            return False
        with self._heal_meta_lock:
            lock = self._heal_locks.setdefault(sid, threading.Lock())
            entry_gen = self._heal_gen.get(sid, 0)
        trace = None
        tracer = None
        from byteps_tpu.core.tracing import (
            get_process_tracer,
            new_trace_id,
            span_args,
        )

        tracer = get_process_tracer()
        if tracer is not None and tracer.enabled and tracer.spans_enabled:
            trace = (new_trace_id(), new_trace_id())
        t0 = time.time()
        with lock:
            with self._heal_meta_lock:
                if self._heal_gen.get(sid, 0) != entry_gen:
                    # a concurrent give-up healed this server while we
                    # waited for the lock — ride its work
                    return True
            counters().bump("resync_attempt", labels={"server": sid})
            ok, replayed = False, 0
            try:
                ok, replayed = self._run_resync(key, sid, trace)
            except Exception:  # noqa: BLE001 — a heal must never leak
                ok = False
            if ok:
                with self._heal_meta_lock:
                    self._heal_gen[sid] = entry_gen + 1
            else:
                counters().bump("resync_giveup", labels={"server": sid})
        if trace is not None:
            tracer.record_span(
                "resync", "RESYNC", t0, time.time() - t0,
                span_args(trace[0], trace[1], server=sid,
                          replayed=replayed, healed=ok),
            )
        return ok

    def _run_resync(self, route_key: int, sid: str, trace) -> tuple:
        """The heal body → (ok, rounds_replayed).  Caller holds the
        server's heal lock.

        1. (Re-)dial the server; a server that cannot be dialed is DOWN,
           not one-sided — that case belongs to eviction/rebuild, so the
           heal fails fast instead of burning the budget.
        2. Op.RESYNC_QUERY for every key this worker journals toward the
           server (plus the triggering key): the reply's per-key ``seen``
           is the newest version of OUR pushes its exactly-once ledger
           absorbed.
        3. Replay, oldest-first, exactly the journaled rounds above each
           ``seen`` watermark through the NORMAL push path (ledger
           dedupe, zombie fence, round publish all apply) — fused-pack
           members replay as plain per-key pushes, which the server sums
           identically.
        """
        from byteps_tpu.comm.journal import get_journal
        from byteps_tpu.comm.retry import Backoff
        from byteps_tpu.comm.transport import (
            decode_resync_state,
            encode_resync_query,
        )

        deadline_at = time.monotonic() + self.cfg.resync_deadline_s
        j = get_journal()
        wid = self._worker_flag()

        def owned(k: int) -> bool:
            try:
                return str(self.server_for(k)) == sid
            except (ValueError, ZeroDivisionError, IndexError, ConnectionError):
                return False

        keys = sorted(
            {route_key} | {k for k in (j.keys() if j else []) if owned(k)}
        )
        backoff = Backoff(base=max(0.01, self.cfg.rpc_backoff_s), cap=1.0)

        def recovery_rpc(k: int, make_msg, errmsg: str):
            """One blocking recovery RPC, re-dialed and re-sent within
            the heal budget; None once the budget (or the server) dies."""
            while True:
                remaining = deadline_at - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return None
                per_try = (
                    min(remaining, max(0.2, self.cfg.rpc_deadline_s))
                    if self.cfg.rpc_deadline_s > 0 else remaining
                )
                try:
                    sc = self._conn_for(k, revive=True)
                except (ConnectionError, OSError):
                    return None  # server not dialable: not the one-sided case
                try:
                    return self._blocking_request(sc, make_msg, errmsg, per_try)
                except ConnectionError:
                    # frames still being lost (the chaos that caused the
                    # give-up): back off and re-dial within the budget
                    if self._stop.wait(min(
                        backoff.next_delay(),
                        max(0.0, deadline_at - time.monotonic()),
                    )):
                        return None

        resp = recovery_rpc(
            route_key,
            lambda seq: Message(
                Op.RESYNC_QUERY, key=route_key, seq=seq, flags=wid,
                payload=encode_resync_query(wid, keys), trace=trace,
            ),
            "resync query failed",
        )
        if resp is None:
            return False, 0
        if resp.op != Op.RESYNC_STATE or resp.status != 0:
            # the server doesn't speak the recovery plane (a pre-parity
            # native binary rejects with nonzero status; current engines
            # — Python AND C++ — both serve it) — fall back to re-init
            return False, 0
        state = decode_resync_state(resp.payload)
        replayed = 0
        for k in keys:
            info = state.get(k)
            if info is None:
                if j is not None and j.entries_after(k, 0):
                    # we journaled pushes for a key the server no longer
                    # holds: its store was lost (restart) — only the init
                    # barrier can rebuild allocation, resync cannot
                    return False, replayed
                continue
            entries = (
                j.entries_after(k, int(info.get("seen", 0))) if j else []
            )
            for e in entries:
                ack = recovery_rpc(
                    k,
                    lambda seq, _k=k, _e=e: Message(
                        Op.PUSH, key=_k, seq=seq, cmd=_e.cmd,
                        version=_e.version, flags=wid, payload=_e.payload,
                        trace=trace,
                    ),
                    f"resync replay failed for key {k}",
                )
                if ack is None or ack.status != 0 or ack.op == Op.WRONG_OWNER:
                    # a redirect mid-replay means the key moved AGAIN
                    # (double migration race): fail this heal — the
                    # give-up path re-runs once the new book lands
                    return False, replayed
                counters().bump(
                    "resync_replayed_rounds", labels={"server": sid}
                )
                replayed += 1
        return True, replayed

    def _blocking_request_retrying(
        self, key: int, make_msg, errmsg: str, use_deadline: bool = True
    ) -> Message:
        """Retrying wrapper for the blocking control RPCs (init-push,
        compressor registration).  Safe to replay: the server keys init
        waiters and compressor registration idempotently (server.py).

        ``use_deadline=False`` for RPCs whose latency depends on PEER
        workers (the init barrier: the server withholds the ack until
        every worker arrives) — the ordinary per-RPC deadline would make
        on-time workers tear down healthy connections whenever one peer
        straggles.  Such RPCs use the separate ``BYTEPS_INIT_DEADLINE_S``
        budget instead (default 0 = none; set it ABOVE worst-case worker
        skew — chaos tests set it small to heal dropped init acks).
        Connection death still fails the wait immediately (cb(None)
        drain) either way, so retries remain live; a hung server during
        a deadline-free init is the scheduler eviction policy's job."""
        from byteps_tpu.comm.retry import Backoff

        backoff = Backoff(base=self.cfg.rpc_backoff_s, cap=2.0)
        deadline = (
            (self.cfg.rpc_deadline_s or None) if use_deadline
            else (self.cfg.init_deadline_s or None)
        )
        try:
            sid = str(self.server_for(key))
        except (ValueError, ZeroDivisionError, IndexError, ConnectionError):
            sid = "?"
        last: Optional[BaseException] = None
        attempt = 0
        redirects = 0
        while attempt <= self.cfg.rpc_retries:
            if attempt:
                counters().bump("rpc_retry", labels={"server": sid})
                if self._stop.wait(backoff.next_delay()):
                    break
            try:
                sc = self._conn_for(key, revive=attempt > 0)
            except (ConnectionError, OSError) as e:
                last = e
                attempt += 1
                continue
            try:
                resp = self._blocking_request(sc, make_msg, errmsg, deadline)
            except ConnectionError as e:
                last = e
                attempt += 1
                continue
            if resp.op == Op.WRONG_OWNER:
                # the key migrated (docs/robustness.md "migration flow"):
                # wait for the redirect's book, re-route, resend.  Chases
                # don't consume the retry budget (the server answered)
                # but are capped against a pathological ping-pong.
                if redirects >= self._max_chases:
                    last = ConnectionError("wrong-owner chase exhausted")
                    break
                redirects += 1
                counters().bump(
                    "wrong_owner_redirect", labels={"server": sid}
                )
                self._wait_map_epoch(
                    resp.version, min(2.0, 0.25 * redirects)
                )
                continue
            return resp
        counters().bump("rpc_giveup")
        raise ConnectionError(errmsg) from last

    @staticmethod
    def _blocking_request(
        sc, make_msg, errmsg: str, timeout: Optional[float] = None
    ) -> Message:
        """Send one server request and block for its ack; raises
        ConnectionError if the connection is dead or dies while waiting
        (the alloc_seq dead-path fires the callback with None).  With a
        ``timeout``, expiry tears the (presumed hung) connection down —
        same policy as the async deadline scanner."""
        done = threading.Event()
        box: list = []
        seq = sc.alloc_seq(lambda msg: (box.append(msg), done.set()))
        if seq >= 0:
            try:
                sc.send_msg(make_msg(seq))
            except OSError:
                # connection died between alloc_seq and send: callers see
                # the same ConnectionError as the dead-connection path
                sc.pop_cb(seq)
                raise ConnectionError(errmsg) from None
        if not done.wait(timeout):
            counters().bump("rpc_deadline_expired")
            sc.close_all()
            done.wait(5.0)  # the drain fires promptly once lanes close
        if not box or box[0] is None:
            raise ConnectionError(errmsg)
        return box[0]

    def _start_recv_loops(self, sc: _ServerConn) -> None:
        """One receiver per lane; all lanes demux into the shared seq-keyed
        callback table (responses come back on the lane that carried the
        request — the server answers per-connection)."""
        threads = [
            threading.Thread(target=self._recv_loop, args=(sc, sock), daemon=True)
            for sock, _ in sc.stripes
        ]
        sc.recv_thread = threads[0]
        for t in threads:
            t.start()

    def _recv_loop(self, sc: _ServerConn, sock) -> None:
        from byteps_tpu.comm.transport import (
            LosslessError,
            checksum_conn_limit,
            frame_checksum,
            recv_header_ex,
            recv_into,
        )
        from byteps_tpu.compression.lossless import decompress_frame

        ck_limit = checksum_conn_limit()
        try:
            while not self._stop.is_set():
                try:
                    (op, status, flags, seq, key, cmd, version, length,
                     trace, crc, lossless) = recv_header_ex(sock)
                    # the callback is popped only AFTER the payload is
                    # fully received: dying mid-payload must leave it for
                    # mark_dead's cb(None) drain, never lose it
                    sink = sc.peek_sink(seq)
                    # a lossless frame's `length` is the container size,
                    # never the caller's raw-sized sink — decode lands in
                    # an owned payload (no zero-copy for compressed frames)
                    zero_copied = (not lossless and sink is not None
                                   and length == len(sink))
                    if zero_copied:
                        # zero-copy: the aggregated payload lands directly
                        # in the caller's result buffer — no intermediate
                        # bytes object, no frombuffer+slice copy
                        recv_into(sock, sink)
                        payload = _ZERO_COPIED
                    else:
                        payload = (
                            _recv_exact(sock, length) if length else b""
                        )
                    if crc is not None and frame_checksum(
                        trace, sink if zero_copied else payload
                    ) != crc:
                        # end-to-end wire integrity (docs/robustness.md):
                        # a corrupted reply is DROPPED before the seq
                        # demux — the callback stays registered so the
                        # deadline/retry machinery re-fetches (a zero-
                        # copy sink holding garbage is harmless: the
                        # retried response overwrites it before the
                        # caller ever wakes).  Repeated mismatches
                        # poison the connection → revival re-dials.
                        fails = sc.note_checksum_fail()
                        counters().bump("wire_checksum_fail", labels={
                            "side": "client",
                            "op": getattr(op, "name", str(op)),
                            "server": getattr(sc, "server_label", "?"),
                        })
                        if ck_limit and fails >= ck_limit:
                            counters().bump("wire_checksum_conn_drop")
                            return
                        continue
                    if lossless:
                        # decompress AFTER integrity passes; a corrupt
                        # container is dropped exactly like a CRC
                        # mismatch — the callback stays registered, the
                        # deadline/retry machinery re-fetches, and
                        # repeated failures poison the connection
                        try:
                            payload = decompress_frame(payload, op=op)
                        except LosslessError:
                            fails = sc.note_checksum_fail()
                            counters().bump("wire_lossless_fail", labels={
                                "side": "client",
                                "op": getattr(op, "name", str(op)),
                                "server": getattr(sc, "server_label", "?"),
                            })
                            if ck_limit and fails >= ck_limit:
                                counters().bump("wire_checksum_conn_drop")
                                return
                            continue
                    if zero_copied:
                        self.zero_copy_pulls += 1
                except (ConnectionError, OSError):
                    return
                cb = sc.pop_cb(seq)
                if cb is not None:
                    cb(
                        Message(
                            op, key=key, payload=payload, seq=seq, cmd=cmd,
                            version=version, status=status, flags=flags,
                        )
                    )
        finally:
            # one lane dying poisons the whole striped connection: close
            # every lane (wakes the sibling receivers).  The DRAIN — fail
            # every pending request with cb(None) so callers never hang in
            # synchronize() — runs only on the LAST lane to exit: sibling
            # receivers may still be writing into callers' zero-copy sinks
            sc.close_all()
            if sc.lane_exited():
                for cb in sc.mark_dead():
                    try:
                        cb(None)
                    except Exception:  # noqa: BLE001
                        pass

    # --- key routing -----------------------------------------------------

    def server_for(self, key: int) -> int:
        """The key's owning server RANK.  Under live resharding this is
        the adopted ownership map's owner; legacy routing hashes over the
        server count (where rank == list index)."""
        omap = self._ownership
        if omap is not None:
            return omap.owner(key)
        if self.num_servers <= 0:
            # transiently-empty book (eviction burst): retryable, unlike
            # the hash fn's ValueError
            raise ConnectionError("no servers in current book")
        return assign_server(
            key,
            self.num_servers,
            fn=self.cfg.key_hash_fn,
            coef=self.cfg.built_in_hash_coef,
            mixed_mode=self.cfg.enable_mixed_mode,
            mixed_bound=self.cfg.mixed_mode_bound,
            num_workers=self.num_workers,
            ring_vnodes=self.cfg.ring_vnodes,
        )

    def _conn_for(self, key: int, revive: bool = False) -> _ServerConn:
        """Route a key from ONE atomic snapshot of the server list.
        During a live resize the list reference swaps under us; hashing
        with ``len(snapshot)`` keeps count and list consistent (reading
        self.num_servers separately could pair the new count with the old
        list → IndexError instead of the designed dead-connection path).

        ``revive=True`` (retry attempts): a dead connection is re-dialed
        in place first — a transient disconnect (chaos van, server
        restart, deadline teardown) heals without scheduler involvement.
        """
        servers = self._servers
        if not servers:
            # a burst of evictions can transiently empty the book;
            # ConnectionError (not the hash fn's ValueError) keeps this
            # on the retry path so the next book heals it
            raise ConnectionError("no servers in current book")
        routing = self._routing
        # the ownership map routes only when its snapshot matches the
        # live list (the two swap together; a mismatch means a rebuild is
        # mid-swap or the client was built without a book — fall back to
        # legacy count-hash routing, which the redirect chase corrects)
        ranks, omap = (
            (routing[1], routing[2]) if routing[0] is servers else ([], None)
        )
        if omap is not None and ranks and len(ranks) == len(servers):
            owner = omap.owner(key)
            try:
                idx = ranks.index(owner)
            except ValueError:
                raise ConnectionError(
                    f"owner rank {owner} not in current book"
                ) from None
        else:
            idx = assign_server(
                key,
                len(servers),
                fn=self.cfg.key_hash_fn,
                coef=self.cfg.built_in_hash_coef,
                mixed_mode=self.cfg.enable_mixed_mode,
                mixed_bound=self.cfg.mixed_mode_bound,
                num_workers=self.num_workers,
                ring_vnodes=self.cfg.ring_vnodes,
            )
        sc = servers[idx]
        if revive and getattr(sc, "dead", False):
            sc = self._revive_conn(idx, sc)
        return sc

    def _revive_conn(self, idx: int, dead_sc) -> _ServerConn:
        """Replace a dead server connection with a fresh dial to the same
        address (server state is per-key, not per-connection, so a revived
        link resumes exactly where the dead one left off — retried pushes
        dedupe server-side).  Raises on dial failure.

        The dial happens OUTSIDE the rebuild lock: a black-holed server
        (no RST, dial blocks until its timeout) must not stall elastic
        RESIZE rebuilds or other keys' revives behind it.  Both lock
        sections re-validate, so a rebuild landing mid-dial wins and the
        late revival is discarded."""
        with self._rebuild_lock:
            if self._stop.is_set():
                raise ConnectionError("client closed")
            servers = self._servers  # re-read: a rebuild may have swapped it
            if idx >= len(servers):
                raise ConnectionError("server set resized")
            cur = servers[idx]
            if cur is not dead_sc and not getattr(cur, "dead", False):
                return cur  # another retry already revived this slot
            host, port = self._server_addrs[idx]
        # revival dials get a deadline-scaled bound: with per-RPC
        # deadlines armed the operator opted into bounded-latency failure
        # handling, and a black-holed server (SYN dropped, no RST) must
        # not pin a retry-executor thread for the full 30s van timeout
        dial_timeout = (
            min(30.0, max(2.0, 4 * self.cfg.rpc_deadline_s))
            if self.cfg.rpc_deadline_s > 0 else 30.0
        )
        fresh = self._new_conn(host, port, dial_timeout)  # lock NOT held
        fresh.server_label = str(idx)
        with self._rebuild_lock:
            servers = self._servers
            if (self._stop.is_set() or idx >= len(servers)
                    or self._server_addrs[idx] != (host, port)):
                fresh.close_all()  # superseded by a rebuild/shutdown
                raise ConnectionError("server set changed during revive")
            cur = servers[idx]
            if cur is not dead_sc and not getattr(cur, "dead", False):
                fresh.close_all()  # another reviver won the race
                return cur
            servers[idx] = fresh
        counters().bump("conn_revive", labels={"server": str(idx)})
        cur.close_all()  # idempotent; frees the old lanes' fds
        return fresh

    # --- data plane ------------------------------------------------------

    def init_tensor(self, key: int, num_elements: int, dtype_id: int,
                    trace: Optional[tuple] = None,
                    async_profile: bool = False,
                    staleness: int = -1,
                    server_opt: Optional[str] = None,
                    server_opt_hp: Optional[dict] = None) -> None:
        """Blocking init-push; doubles as the cross-worker barrier for this
        key (InitTensor blocking ZPush, operations.cc:283-414).

        Wire payload is language-neutral (u64 nelems + u32 dtype, network
        order) so the native C++ server parses it directly.  Carries the
        worker flag so a replayed init REPLACES this worker's barrier
        waiter instead of double-counting it (server.py).  ``trace``
        rides the optional trace-context header field; a retried init
        keeps its span.

        The ``version`` field carries the init-idempotency token
        (:meth:`_init_token`), fixed across this init's retries: a retry
        arriving AFTER the barrier released is acked from the server's
        completed-barrier record instead of re-parked — without it, the
        retrier's released peers never re-init the key and the short
        barrier strands the retry until its budget dies.

        ``async_profile`` (docs/async.md): the key is declared ASYNC —
        the server applies its pushes immediately and serves pulls from
        current state, bounded by ``staleness`` (-1 = unbounded).  The
        profile rides a 5-byte payload extension (u8 profile + i32
        staleness) that sync keys never send, so pre-tenancy servers
        keep seeing the exact 12-byte INIT they always parsed — and the
        native C++ engine, which has no async plane, rejects the
        extended form with a clean ``status=1`` echo (the Python-engine
        fallback rule, docs/async.md).

        ``server_opt`` (docs/architecture.md "Server-side optimizer"):
        the key declares a server-side update rule — bit 1 of the same
        profile byte, followed by the rule block (name + canonical-JSON
        ``server_opt_hp``), so the server runs the optimizer and this
        worker pulls updated parameters.  Engines without the update
        plane reject with the same clean status echo."""
        import struct

        token = self._init_token(key)
        payload = struct.pack("!QI", num_elements, dtype_id)
        profile = (1 if async_profile else 0) | (2 if server_opt else 0)
        if profile:
            payload += struct.pack("!Bi", profile, int(staleness))
        if server_opt:
            from byteps_tpu.comm.transport import encode_server_opt_block
            from byteps_tpu.server.update_rules import canonical_hp

            payload += encode_server_opt_block(
                server_opt, canonical_hp(server_opt_hp or {})
            )
        resp = self._blocking_request_retrying(
            key,
            lambda seq: Message(
                Op.INIT,
                key=key,
                seq=seq,
                flags=self._worker_flag(),
                version=token,
                payload=payload,
                trace=trace,
            ),
            f"server connection lost during init of key {key}",
            # the init ack legitimately waits for PEER workers — a
            # per-attempt deadline would punish stragglers' peers
            use_deadline=False,
        )
        if resp is not None and resp.status != 0:
            # the server REFUSED this init with a clean status echo —
            # the native C++ engine rejecting an async profile or a
            # job-namespaced key (docs/async.md), or a genuinely
            # incompatible server.  Failing fast here is the whole
            # point of the clean rejection: training on would leave
            # every later push/pull status-echoed too, and the job
            # would silently run on uninitialized state.
            from byteps_tpu.common.tenancy import job_of_key

            if server_opt:
                why = (f"the server-side optimizer plane (rule "
                       f"{server_opt!r}) needs Python-engine servers — "
                       "see docs/architecture.md")
            elif async_profile:
                why = ("async push_pull needs Python-engine servers "
                       "— see docs/async.md")
            elif job_of_key(key):
                why = (f"job {job_of_key(key)} keys need Python-engine "
                       "servers (multi-tenant namespaces are rejected "
                       "by the C++ engine) — see docs/async.md")
            else:
                why = "server refused the init"
            raise RuntimeError(
                f"server refused init for key {key} (status "
                f"{resp.status}): {why}"
            )

    def push(
        self,
        key: int,
        payload: bytes,
        dtype_id: int,
        version: int,
        cb: Callable[[], None],
        request_type: RequestType = RequestType.DEFAULT_PUSH_PULL,
        on_error: Optional[Callable[[], None]] = None,
        abort_check: Optional[Callable[[], bool]] = None,
        trace: Optional[tuple] = None,
        lossless: Optional[bool] = None,
    ) -> None:
        """Async push; ``cb`` fires on server ack (ZPush,
        core_loops.cc:538-582); ``on_error`` fires once retries are
        exhausted after connection failures (BYTEPS_RPC_RETRIES);
        ``abort_check`` fences pending retries once the caller abandons
        the operation.

        Replay-safe: the worker flag + version lets the server suppress a
        retransmitted push whose original WAS summed (ack lost), so
        summation stays exactly-once under retry.  ``trace`` is the
        (trace_id, span_id) context propagated on the wire — built ONCE
        into the closure, so every retry attempt re-sends the SAME span
        (the server's dedupe annotation then lands on the right one).

        ``lossless=True`` asks the transport for the lossless frame
        transform on this push (the tuner's per-key lossless arm for
        keys whose lossy codec lost) — the frame ships compressed only
        when the container actually wins; Python wire only (the native
        client's send path doesn't stamp the flag)."""
        cmd = get_command_type(request_type, dtype_id)
        flags = self._worker_flag()
        self._async_rpc(
            lambda seq: Message(
                Op.PUSH, key=key, seq=seq, payload=payload, cmd=cmd,
                version=version, flags=flags, trace=trace,
                lossless=lossless,
            ),
            key,
            deliver=lambda msg: cb(),
            on_error=on_error,
            abort_check=abort_check,
        )

    def push_fused(
        self,
        members: List[tuple],
        cb: Callable[[list], None],
        on_error: Optional[Callable[[], None]] = None,
        abort_check: Optional[Callable[[], bool]] = None,
        trace: Optional[tuple] = None,
        member_spans: Optional[List[int]] = None,
    ) -> None:
        """One multi-key fused push+pull RPC (Op.FUSED; docs/perf.md).

        ``members`` is ``[(key, cmd, version, payload), ...]`` — small
        same-server partitions packed by the engine's FUSE stage.  The
        whole frame shares ONE seq, ONE deadline token, and ONE retry
        state (vs. 2 × len(members) for unfused push+pull pairs), and is
        routed by its first member's key.  ``cb`` receives the decoded
        reply ``[(key, version, merged_bytes), ...]``.

        Replay-safe like :meth:`push`: the frame carries the worker flag,
        and the server runs every sub-push through the per-(worker, key)
        exactly-once ledger — a retransmitted frame re-sums nothing that
        already landed, atomically per member key.

        Tracing: ``trace`` is the PACK's span (outer header field);
        ``member_spans`` (one id per member, same order) ride the fused
        body's optional trailer so the server can stamp per-member child
        spans.  Both are fixed per frame — retries keep their spans."""
        import struct as _struct

        from byteps_tpu.comm.transport import (
            decode_fused_reply,
            encode_fused_push,
        )

        frame = encode_fused_push(members, span_ids=member_spans)
        route_key = members[0][0]
        flags = self._worker_flag()
        # generation fence: the pack was grouped under the CURRENT server
        # set; if a resize lands before any attempt (first or retry), the
        # members may no longer share a server — fail fast to on_error
        # (the engine regroups via its unfuse fallback) instead of
        # re-shipping mis-homed keys until retries exhaust
        gen0 = self.server_generation

        def deliver(msg: Message) -> None:
            # decode INSIDE the delivery path: a corrupted reply (chaos
            # corrupt fault surviving framing, buggy server) must route to
            # the caller's error handler — raising here would unwind into
            # the recv lane AFTER the callback was popped and the deadline
            # cleared, stranding every member with no retry
            try:
                reply = decode_fused_reply(msg.payload)
            except (ValueError, _struct.error):
                counters().bump("fused_reply_malformed")
                if on_error is not None:
                    on_error()
                return
            cb(reply)

        self._async_rpc(
            lambda seq: Message(
                Op.FUSED, key=route_key, seq=seq, payload=frame,
                cmd=len(members), flags=flags, trace=trace,
            ),
            route_key,
            deliver=deliver,
            on_error=on_error,
            abort_check=abort_check,
            precheck=lambda: self.server_generation == gen0,
            # no frame-level heal and no redirect chase: the fused error
            # path is the unfuse fallback, whose per-key RPCs each carry
            # their own heal (and chase WRONG_OWNER individually)
            heal=False,
            chase=False,
        )

    def pull(
        self,
        key: int,
        version: int,
        cb: Callable[[bytes], None],
        dtype_id: int = 0,
        request_type: RequestType = RequestType.DEFAULT_PUSH_PULL,
        on_error: Optional[Callable[[], None]] = None,
        payload: bytes = b"",
        sink: Optional[memoryview] = None,
        abort_check: Optional[Callable[[], bool]] = None,
        trace: Optional[tuple] = None,
    ) -> None:
        """Async pull; ``cb`` receives the aggregated payload (ZPull,
        core_loops.cc:584-618); ``on_error`` fires if the server connection
        dies before the response.  ``payload`` carries the request body for
        row-sparse pulls (the row indices to gather).

        ``sink``: caller-owned writable buffer; when the response length
        matches, the payload is received INTO it (zero payload copies) and
        ``cb`` gets the ``_ZERO_COPIED`` sentinel instead of bytes.

        Pulls are read-only, hence idempotent — retried freely.  A retried
        sink pull never races a late writer: retry only happens after the
        previous attempt's connection is fully dead (all lanes exited)."""
        cmd = get_command_type(request_type, dtype_id)
        self._async_rpc(
            lambda seq: Message(
                Op.PULL, key=key, seq=seq, payload=payload, cmd=cmd,
                version=version, trace=trace,
            ),
            key,
            deliver=lambda msg: cb(msg.payload),
            on_error=on_error,
            sink=sink,
            abort_check=abort_check,
        )

    def register_compressor(self, key: int, kwargs: Dict[str, str]) -> None:
        """Ship compressor config to the owning server
        (kCompressedPushPull init push, operations.cc:396-408).

        Payload is newline-separated ``key=value`` text — parseable by the
        Python and native C++ servers alike.  Replay-idempotent (the
        server overwrites the key's chain), so the retrying path applies."""
        payload = "\n".join(f"{k}={v}" for k, v in sorted(kwargs.items())).encode()
        self._blocking_request_retrying(
            key,
            lambda seq: Message(
                Op.REGISTER_COMPRESSOR, key=key, seq=seq, payload=payload
            ),
            f"server connection lost registering compressor for key {key}",
        )

    def set_compression_lr(self, lr: float) -> None:
        """Broadcast the optimizer lr to every server's EF chains (flag
        bit 0 on REGISTER_COMPRESSOR, payload = big-endian f64 — the
        wire replacement for the reference's lr.s mmap,
        vanilla_error_feedback.h:44-58).  Fire-and-forget: EF lr scaling
        is a numerical refinement, not a correctness barrier."""
        import struct as _struct

        payload = _struct.pack("!d", float(lr))
        for sc in self._servers:
            try:
                seq = sc.alloc_seq(lambda msg: None)
                if seq < 0:
                    continue  # dead server already handled by the data path
                sc.send_msg(
                    Message(Op.REGISTER_COMPRESSOR, seq=seq, payload=payload, flags=1)
                )
            except (ConnectionError, OSError):
                continue  # dead server already handled by the data path
