"""Scheduler node: registration rendezvous + global barrier service.

Replaces ps-lite's scheduler/Postoffice role (SURVEY §2.4): every worker
and server REGISTERs at ``DMLC_PS_ROOT_URI:DMLC_PS_ROOT_PORT``; once the
expected population (DMLC_NUM_WORKER + DMLC_NUM_SERVER) is present the
scheduler pushes an ADDRBOOK (per-role rank + server address list) to every
node, the equivalent of ps::StartPS's rendezvous (global.cc:289-294,
server.cc:500-509).  Persistent connections then serve BARRIER requests
(ps::Postoffice::Barrier).

Elastic rejoin: a REGISTER arriving after the population is full replaces
the node's previous registration and immediately receives the current
ADDRBOOK, flagged as recovery (is_recovery(), global.cc:291).  Rejoins are
matched on a *stable node uid* carried in the REGISTER payload (workers
register with host=''/port=0, so an address match would alias them all);
clients persist the uid across suspend/resume.

Control-plane payloads are JSON, not pickle: the scheduler listens on
0.0.0.0 and must never unpickle attacker-reachable bytes.  Arbitrary
object transfer stays on the data plane's explicitly documented
``broadcast_object`` API.

Failure detection (ps-lite heartbeat equivalent, SURVEY §5.3): every
message from a registered node refreshes its last-seen stamp; nodes ping
every ``BYTEPS_HEARTBEAT_INTERVAL`` seconds and Op.QUERY returns per-node
heartbeat ages.

Crash recovery (docs/robustness.md "Control-plane recovery"): the
scheduler is stateless-restartable.  Every instance mints an
**incarnation id** stamped into every book; nodes refuse books from an
older incarnation (a zombie scheduler racing its successor).  A
restarted scheduler rebuilds its registration table from the survivors'
re-REGISTERs — each carries the node's persisted uid, last-known rank
(honored when free), membership epoch, and ownership-map epoch — and
fences its first books ABOVE the maximum reported epochs, so a reborn
control plane can never hand out state older than what a live node
already acted on.  ``BYTEPS_SCHED_REJOIN_WINDOW_S`` bounds how long the
rebirth waits for every previously-reported rank before adopting the
partial population (no books ship, and therefore no eviction can fire,
until then — slow reconnectors are not mass-evicted).

Liveness POLICY (docs/robustness.md): with ``BYTEPS_DEAD_NODE_TIMEOUT_S``
set (> heartbeat interval), a monitor thread EVICTS any registered node
whose heartbeat age exceeds the threshold — a crashed node stops
heartbeating, a hung one keeps its connection open but silent; both age
out.  Eviction shrinks the expected population (so in-flight rounds and
barriers complete without the dead node's contribution), bumps the
membership ``epoch``, and broadcasts RESIZE_SEQ address books — the same
recovery path elastic suspend/resume uses, now triggered automatically.
Each book carries the epoch and cumulative eviction totals so workers'
telemetry counters reflect the degradation.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from byteps_tpu.comm.transport import (
    Message,
    Op,
    close_socket,
    listen,
    recv_message,
    send_message,
)

GROUP_WORKERS = 1
GROUP_SERVERS = 2
GROUP_ALL = 3

#: seq used for unsolicited ADDRBOOK broadcasts after an elastic resize —
#: distinguishes them from request/response pairs on the control conn
RESIZE_SEQ = 0xFFFFFFFF


@dataclass
class _Node:
    rank: int
    host: str
    port: int
    conn: Any
    send_lock: Any
    uid: str
    # multi-tenant identity + QoS (docs/async.md): which job this
    # worker belongs to and the job's declared weighted share /
    # admission quota — aggregated into every book's ``jobs`` map so
    # servers can weight service and meter admission per tenant
    job: int = 0
    job_priority: int = 1
    job_quota_mbps: float = 0.0


class Scheduler:
    """Run with role=scheduler (the reference starts it via
    ``import byteps.server`` with DMLC_ROLE=scheduler,
    server/__init__.py:21-27)."""

    def __init__(
        self,
        num_workers: int,
        num_servers: int,
        host: str = "0.0.0.0",
        port: int = 0,
        dead_node_timeout: Optional[float] = None,
        incarnation: Optional[int] = None,
        rejoin_window: Optional[float] = None,
    ):
        self.num_workers = num_workers
        self.num_servers = num_servers
        #: incarnation id (docs/robustness.md "Control-plane recovery"):
        #: a fresh value per scheduler PROCESS lifetime, stamped into
        #: every book.  Nodes track the highest value seen and refuse
        #: books from an older incarnation — the zombie-scheduler fence,
        #: the control-plane twin of the zombie-worker fence.  Wall-clock
        #: ns: strictly increasing across restarts on one host, and a
        #: successor on another host still compares correctly to NTP
        #: skew precision (injectable for deterministic tests).
        self.incarnation = (
            int(incarnation) if incarnation is not None else time.time_ns()
        )
        #: rejoin grace (BYTEPS_SCHED_REJOIN_WINDOW_S): how long a
        #: RESTARTED scheduler waits for every previously-reported rank
        #: to re-REGISTER before adopting the partial population.  Armed
        #: lazily by the first registrant that reports a prior
        #: incarnation (``last_rank`` in its payload) — a fresh first
        #: boot never starts the timer, so bring-up behavior is
        #: unchanged.
        if rejoin_window is None:
            rejoin_window = float(
                os.environ.get("BYTEPS_SCHED_REJOIN_WINDOW_S", "15") or "15"
            )
        self.rejoin_window = rejoin_window
        #: registrants that reported a prior incarnation (rejoiners);
        #: nonzero marks this instance as a REBIRTH — its first books
        #: fence epochs above every report and carry is_recovery
        self._rejoin_reports = 0
        self._grace_thread: Optional[threading.Thread] = None
        # liveness policy threshold; None → BYTEPS_DEAD_NODE_TIMEOUT_S
        # (0 disables eviction: ages stay observable via Op.QUERY only)
        if dead_node_timeout is None:
            dead_node_timeout = float(
                os.environ.get("BYTEPS_DEAD_NODE_TIMEOUT_S", "0") or 0
            )
        self.dead_node_timeout = dead_node_timeout
        if dead_node_timeout > 0:
            # eviction is heartbeat-driven: with heartbeats disabled (or
            # slower than the threshold) every healthy node's age grows
            # past the timeout during any compute-only stretch and the
            # whole cluster gets evicted — warn loudly
            hb = float(os.environ.get("BYTEPS_HEARTBEAT_INTERVAL", "5") or 0)
            if hb <= 0 or dead_node_timeout < 3 * hb:
                from byteps_tpu.common import logging as bpslog

                bpslog.warning(
                    "BYTEPS_DEAD_NODE_TIMEOUT_S=%.1f needs heartbeats ≥3x "
                    "faster (BYTEPS_HEARTBEAT_INTERVAL=%.1f) — healthy "
                    "nodes risk eviction", dead_node_timeout, hb,
                )
        #: membership epoch: bumped on every topology-visible change
        #: (resize, dead-slot adoption, eviction) and carried in every
        #: address book
        self.epoch = 0
        #: key→server OWNERSHIP map epoch (docs/robustness.md "migration
        #: flow"): bumped only when the SERVER set changes (join, leave,
        #: eviction, dead-slot adoption with a new address), so worker
        #: churn never triggers key migration.  Carried in every book;
        #: with BYTEPS_ELASTIC_RESHARD servers migrate re-homed keys and
        #: workers chase WRONG_OWNER redirects stamped with it.
        self.map_epoch = 0
        self._map_sig: Optional[tuple] = None
        #: elastic resharding policy (BYTEPS_ELASTIC_RESHARD): scale-down
        #: then DRAINS dropped servers (they migrate their keys out and
        #: stop themselves) instead of SHUTDOWN-ing them cold
        self.reshard = os.environ.get(
            "BYTEPS_ELASTIC_RESHARD", ""
        ).lower() not in ("", "0", "false", "no", "off")
        #: dropped servers awaiting their drain book (sent after the map
        #: epoch bump in _complete_recovery, so the book they drain
        #: against is the settled new topology)
        self._pending_drains: List[_Node] = []
        #: cumulative evictions per role, shipped in books for telemetry
        self.eviction_totals: Dict[str, int] = {"worker": 0, "server": 0}
        self._sock, self.port = listen(host, port)
        self._lock = threading.Lock()
        self._nodes: Dict[str, List[_Node]] = {"worker": [], "server": []}
        self._addrbook_sent = False
        # (group, barrier_round) → list of (conn, send_lock, seq)
        self._barriers: Dict[Tuple[int, int], List] = {}
        self._barrier_round: Dict[int, int] = {GROUP_WORKERS: 0, GROUP_SERVERS: 0, GROUP_ALL: 0}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # conn → (role, rank) for heartbeat attribution
        self._conn_ids: Dict[Any, Tuple[str, int]] = {}
        self._last_seen: Dict[Tuple[str, int], float] = {}
        # connections of recovering nodes: their first barrier releases
        # immediately (the rest of the cluster is not at a barrier)
        self._recovered_conns: set = set()
        # registrations parked until the (resized) population is complete:
        # a worker resuming with num_servers+k can only receive its address
        # book once the new server has actually registered
        self._parked_regs: List[Tuple[Any, Any, str, int, int]] = []
        #: a resize-initiating worker was parked; broadcast when it flushes
        self._pending_broadcast = False
        # cluster-wide metrics aggregate (docs/observability.md): every
        # node piggybacks metric DELTAS on its heartbeat; they fold in
        # here — counters labeled by {role, rank} so one sick node stays
        # visible, histograms merged bucket-wise into the cluster shape.
        # Served on BYTEPS_METRICS_PORT: one scrape sees the whole job.
        from byteps_tpu.core.telemetry import MetricsRegistry

        self.metrics_agg = MetricsRegistry()
        # the ownership map's version, scrapeable from the cluster
        # aggregate so an operator (tools/bps_top.py) can watch a
        # migration settle next to the per-server owned-key gauges the
        # servers heartbeat in
        self.metrics_agg.gauge_fn("cluster_map_epoch", lambda: self.map_epoch)
        # control-plane recovery surface (docs/robustness.md): the
        # incarnation an operator's bps_top is watching, and how many
        # expected nodes have NOT yet re-registered with this instance
        # (nonzero only during a rebirth's rejoin window)
        self.metrics_agg.gauge_fn(
            "cluster_sched_incarnation", lambda: self.incarnation
        )
        self.metrics_agg.gauge_fn(
            "cluster_rejoining_nodes", self._rejoining_count
        )
        # cluster step matrix (docs/observability.md "Flight recorder &
        # doctor"): every node piggybacks a compact flight-ledger tail
        # on its heartbeat; the matrix answers "who is the straggler
        # THIS step" and exports cluster_straggler_rank to the aggregate
        from byteps_tpu.core.flightrec import ClusterFlight

        self.flight = ClusterFlight()
        self.flight.attach(self.metrics_agg)
        # adaptive control plane (docs/autotune.md): with BYTEPS_AUTOTUNE
        # the scheduler hosts a closed-loop policy engine that consumes
        # the cluster aggregate + flight matrix + server hot-key reports
        # each sweep and ships fleet decisions as a versioned ``tuning``
        # section (plus ``ring_overrides``) in every book.  Off (the
        # default): self.tuner is None and books stay byte-for-byte the
        # legacy shape.
        from byteps_tpu.core.autotune import tuner_enabled

        self.tuner = None
        if tuner_enabled():
            from byteps_tpu.core.autotune import AutoTuner

            self.tuner = AutoTuner(
                registry=self.metrics_agg, reshard=self.reshard
            )
            self.metrics_agg.gauge_fn(
                "cluster_tuning_epoch", lambda: self.tuner.state.epoch
            )
        self._metrics_http = None
        # scheduler-link fault injection (BYTEPS_CHAOS_SCHED under a
        # chaos van): accepted control connections get the same
        # send-side fault layer the data plane's listeners wrap with,
        # so scheduler→node frames (ADDRBOOK, barrier releases, PING
        # acks) are chaos-targetable too
        self._chaos_params = None
        from byteps_tpu.comm.chaos import control_chaos_enabled

        if control_chaos_enabled():
            from byteps_tpu.comm.chaos import ChaosParams

            self._chaos_params = ChaosParams.from_env()

    def _rejoining_count(self) -> int:
        """Expected-but-absent node count while the registration table
        is being rebuilt (0 once books have shipped).  Lock-free reads:
        exposition-time gauge sampling may run under the registry lock,
        and int/len reads are GIL-atomic."""
        if self._addrbook_sent:
            return 0
        present = len(self._nodes["worker"]) + len(self._nodes["server"])
        return max(0, self.num_workers + self.num_servers - present)

    def start(self) -> None:
        t = threading.Thread(target=self._accept_loop, name="sched-accept", daemon=True)
        t.start()
        self._threads.append(t)
        if self.dead_node_timeout > 0:
            m = threading.Thread(
                target=self._monitor_loop, name="sched-liveness", daemon=True
            )
            m.start()
            self._threads.append(m)
        if self.tuner is not None:
            a = threading.Thread(
                target=self._tuner_loop, name="sched-autotune", daemon=True
            )
            a.start()
            self._threads.append(a)
        port = int(os.environ.get("BYTEPS_METRICS_PORT", "0") or 0)
        if port > 0:
            from byteps_tpu.core.telemetry import serve_metrics

            self._metrics_http = serve_metrics(
                port, self.metrics_agg.render_prometheus
            )

    # --- adaptive control plane (docs/autotune.md) -----------------------

    def _tuner_loop(self) -> None:
        while not self._stop.wait(self.tuner.cfg.interval_s):
            try:
                self._tuner_sweep_once()
            except Exception as e:  # noqa: BLE001 — the loop must live
                from byteps_tpu.common import logging as bpslog

                bpslog.warning("autotune sweep error: %r", e)

    def _tuner_view(self) -> dict:
        """Assemble one sweep's input view: per-server load + hottest
        keys (heartbeat hot reports), latest per-worker step seconds
        (cluster flight matrix), fusion counter totals + the fleet
        fusion-threshold gauge, and the per-codec
        ``compression_auto_off`` vote counts — all from surfaces the
        telemetry plane already maintains."""
        loads, hot_keys, owned = self.tuner.drain_hot()
        steps: Dict[int, float] = {}
        # per-stage dwell totals across the worker rows of the flight
        # matrix (each record's ``st`` is already a per-step delta) —
        # the fusion-threshold walk deltas these against its previous
        # sweep, so the walk sees where step time went, not just counts
        dwell: Dict[str, float] = {}
        for who, recs in self.flight.matrix().items():
            if not who.startswith("worker"):
                continue
            for r in reversed(recs):
                if r.get("k") == "step" and r.get("dur"):
                    steps[who] = float(r["dur"])
                    break
            for r in recs:
                for stage, nv in (r.get("st") or {}).items():
                    try:
                        dwell[stage] = dwell.get(stage, 0.0) + float(nv[1])
                    except (TypeError, ValueError, IndexError):
                        continue
        flat = self.metrics_agg.counters.snapshot()
        labeled = self.metrics_agg.counters.snapshot_labeled()
        votes: Dict[str, set] = {}
        for lkey, v in (labeled.get("compression_auto_off") or {}).items():
            ld = dict(lkey)
            codec = ld.get("codec")
            if v > 0 and codec and ld.get("role", "worker") == "worker":
                votes.setdefault(codec, set()).add(ld.get("rank", "?"))
        # third consensus arm: entropy-probe verdicts (one per worker
        # per codec) — same rank-dedup shape as the codec_off votes
        lz_votes: Dict[str, set] = {}
        for lkey, v in (
            labeled.get("compression_auto_lossless") or {}
        ).items():
            ld = dict(lkey)
            codec = ld.get("codec")
            if v > 0 and codec and ld.get("role", "worker") == "worker":
                lz_votes.setdefault(codec, set()).add(ld.get("rank", "?"))
        # the fleet fusion threshold the workers actually run (gauge
        # per {role, rank}; max is the fleet value — launch configs
        # agree in practice, and the tuner's own state wins once set).
        # Copied under the registry lock: heartbeat merges resize the
        # dict concurrently.
        with self.metrics_agg._lock:
            gauges = dict(self.metrics_agg._gauges)
        thr = 0.0
        for (name, _lk), v in gauges.items():
            if name == "fusion_threshold_bytes":
                thr = max(thr, float(v))
        with self._lock:
            ranks = [n.rank for n in self._nodes["server"]]
            nw = len(self._nodes["worker"])
        return {
            "server_ranks": ranks,
            "num_workers": nw,
            "steps": steps,
            "server_load": loads,
            "hot_keys": hot_keys,
            "owned": owned,
            "fusion": {
                "threshold": thr,
                "wire_rpc": flat.get("wire_rpc", 0),
                "fused_frames": flat.get("fused_frames", 0),
                "fused_keys": flat.get("fused_keys", 0),
                "dwell": dwell,
            },
            "codec_votes": {c: len(rs) for c, rs in votes.items()},
            "codec_lossless_votes": {
                c: len(rs) for c, rs in lz_votes.items()
            },
        }

    def _tuner_sweep_once(self) -> None:
        res = self.tuner.sweep(self._tuner_view())
        if not res["changed"]:
            return
        with self._lock:
            if res["map_changed"]:
                # key placement changed (rebalance or its rollback): the
                # ownership epoch moves WITH the override set so servers
                # start a migration wave and stale clients chase — the
                # exact PR 8 plane, tuner-initiated
                self.map_epoch += 1
            if not self._addrbook_sent:
                return  # bring-up: the first books carry the state
            for r in ("worker", "server"):
                for node in self._nodes[r]:
                    self._send_addrbook_to(
                        node.conn, node.send_lock, r, node.rank, RESIZE_SEQ
                    )

    def _store_uploaded_bundles(self, ident, bundles) -> None:
        """Fleet-central flight bundles (docs/observability.md "Flight
        recorder & doctor"): nodes with ``BYTEPS_FLIGHT_UPLOAD`` attach
        compact trigger bundles to their heartbeat; they land under the
        scheduler's ``BYTEPS_FLIGHT_DIR`` beside the tuner's decision
        bundles, so an incident's evidence and the control loop's
        reaction sit in one place."""
        base = os.environ.get("BYTEPS_FLIGHT_DIR") or "./flight_bundles"
        who = f"{ident[0]}{ident[1]}" if ident else "unknown"
        for b in bundles or ():
            if not isinstance(b, dict):
                continue
            try:
                path = os.path.join(
                    base,
                    f"{time.strftime('%Y%m%d-%H%M%S')}-{who}"
                    f"-step{b.get('step', 0)}-{b.get('rule', 'trigger')}",
                )
                os.makedirs(path, exist_ok=True)
                with open(os.path.join(path, "trigger.json"), "w") as f:
                    json.dump(b, f, indent=2, default=str)
            except OSError:
                continue
            self.metrics_agg.counters.bump("flight_bundle_rx")

    # --- liveness policy (BYTEPS_DEAD_NODE_TIMEOUT_S) --------------------

    def _monitor_loop(self) -> None:
        tick = max(0.05, min(1.0, self.dead_node_timeout / 4))
        while not self._stop.wait(tick):
            try:
                self._evict_dead_once()
            except Exception as e:  # noqa: BLE001 — the monitor must live
                from byteps_tpu.common import logging as bpslog

                bpslog.warning("liveness monitor error: %r", e)

    def _evict_dead_once(self) -> None:
        """Evict every registered node whose heartbeat age exceeds the
        threshold, then re-broadcast the shrunken topology — crashed AND
        hung nodes alike stop refreshing their stamp, so both age out."""
        now = time.monotonic()
        doomed: List[Tuple[str, _Node]] = []
        with self._lock:
            if not self._addrbook_sent:
                return  # bring-up grace: nobody heartbeats before the book
            for role in ("worker", "server"):
                for n in self._nodes[role]:
                    age = now - self._last_seen.get((role, n.rank), now)
                    if age > self.dead_node_timeout:
                        doomed.append((role, n))
            if not doomed:
                return
            from byteps_tpu.common import logging as bpslog

            for role, n in doomed:
                bpslog.warning(
                    "evicting dead %s rank=%d uid=%s (heartbeat age > %.1fs)",
                    role, n.rank, n.uid, self.dead_node_timeout,
                )
                self._nodes[role].remove(n)
                self._conn_ids.pop(n.conn, None)
                self._last_seen.pop((role, n.rank), None)
                self._recovered_conns.discard(n.conn)
                if role == "worker":
                    self.num_workers = max(0, self.num_workers - 1)
                else:
                    self.num_servers = max(0, self.num_servers - 1)
                self.eviction_totals[role] += 1
            self.epoch += 1
            # a server eviction re-homes its keys: new ownership epoch
            # (worker evictions leave the map untouched)
            self._bump_map_epoch_locked()
            # survivors adopt the shrunken topology (workers rebuild their
            # server set / adopt the worker count; servers complete
            # partial rounds) — the elastic recovery path, auto-triggered
            for r in ("worker", "server"):
                for node in self._nodes[r]:
                    self._send_addrbook_to(
                        node.conn, node.send_lock, r, node.rank, RESIZE_SEQ
                    )
            # scrub the dead nodes' pending barrier entries FIRST: a stale
            # waiter would both satisfy a shrunken barrier early (a live
            # member never arrived) and skew the round counter, stranding
            # the late member in the next round
            doomed_conns = {id(n.conn) for _, n in doomed}
            for key_waiters in self._barriers.values():
                key_waiters[:] = [
                    w for w in key_waiters if id(w[0]) not in doomed_conns
                ]
            # a barrier the dead node would have joined can now be full
            self._release_satisfied_barriers_locked()
        for role, n in doomed:
            # FIN wakes a hung-but-alive node's control reader so it
            # learns it was expelled instead of waiting forever
            close_socket(n.conn)
            # and its row leaves the step matrix — a dead rank's frozen
            # last-step duration must not keep feeding the straggler
            # median (it can rejoin via the restart-detection path)
            self.flight.forget(role, n.rank)

    def _bump_map_epoch_locked(self) -> bool:
        """Advance the ownership-map epoch iff the server set actually
        changed (identity: sorted (rank, host, port)).  Caller holds the
        lock.  Worker-only membership events keep the map epoch — and
        therefore key placement — untouched."""
        sig = tuple(
            sorted((n.rank, n.host, n.port) for n in self._nodes["server"])
        )
        if sig == self._map_sig:
            return False
        self._map_sig = sig
        self.map_epoch += 1
        return True

    def _scrub_barrier_waiters_locked(self, dead_conn) -> None:
        """Drop every parked barrier waiter registered on ``dead_conn``
        (a connection its node has abandoned).  Caller holds the lock."""
        for key_waiters in self._barriers.values():
            key_waiters[:] = [
                w for w in key_waiters if w[0] is not dead_conn
            ]

    def _release_satisfied_barriers_locked(self) -> None:
        """After a group shrinks, pending barriers may already be full —
        release them or every survivor hangs.  Caller holds the lock."""
        for (group, rnd), waiters in list(self._barriers.items()):
            size = self._group_size(group)
            if 0 < size <= len(waiters):
                self._barrier_round[group] = max(
                    self._barrier_round[group], rnd + 1
                )
                del self._barriers[(group, rnd)]
                for wconn, wlock, wseq in waiters:
                    try:
                        send_message(
                            wconn, Message(Op.BARRIER, seq=wseq, flags=group),
                            wlock,
                        )
                    except (ConnectionError, OSError):
                        pass

    def stop(self) -> None:
        self._stop.set()
        if self._metrics_http is not None:
            self._metrics_http.close()
            self._metrics_http = None
        try:
            self._sock.close()
        except OSError:
            pass

    def crash(self) -> None:
        """Die abruptly — the in-process equivalent of ``kill -9``: every
        fd closes with no goodbye frame, exactly what the kernel does to
        a SIGKILLed scheduler (peers observe FIN/RST, nothing else).  No
        drain, no books, no SHUTDOWNs.  Chaos/tests helper: a successor
        constructed on the same (host, port) rebuilds its registration
        table from the survivors' re-REGISTERs (docs/robustness.md
        "Control-plane recovery")."""
        self.stop()
        with self._lock:
            conns = [
                n.conn for role in ("worker", "server")
                for n in self._nodes[role]
            ]
        for conn in conns:
            close_socket(conn)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._chaos_params is not None:
                # scheduler-side half of BYTEPS_CHAOS_SCHED: faults on
                # the response direction (ADDRBOOK drops etc.), drawn
                # from the control-plane index stream so data-plane
                # schedules never shift
                from byteps_tpu.comm.chaos import (
                    ChaosSocket,
                    _next_ctrl_conn_index,
                )

                conn = ChaosSocket(
                    conn, self._chaos_params, _next_ctrl_conn_index(),
                    peer_port=self.port,
                )
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        try:
            while not self._stop.is_set():
                msg = recv_message(conn)
                self._touch(conn)
                if msg.op == Op.REGISTER:
                    self._handle_register(conn, send_lock, msg)
                elif msg.op == Op.BARRIER:
                    self._handle_barrier(conn, send_lock, msg)
                elif msg.op == Op.PING:
                    if msg.payload:
                        self._merge_metric_delta(conn, msg.payload)
                    send_message(conn, Message(Op.PING, seq=msg.seq), send_lock)
                elif msg.op == Op.QUERY:
                    send_message(
                        conn,
                        Message(Op.QUERY, seq=msg.seq, payload=json.dumps(self.liveness()).encode()),
                        send_lock,
                    )
                elif msg.op == Op.SHUTDOWN:
                    send_message(conn, Message(Op.SHUTDOWN, seq=msg.seq), send_lock)
                    return
        except (ConnectionError, OSError):
            return
        except Exception as e:  # noqa: BLE001
            # malformed payload on the attacker-reachable port (bad JSON,
            # bad UTF-8, missing fields) must not kill the serve thread
            # or leak the fd — and the operator needs a trace of it
            from byteps_tpu.common import logging as bpslog

            bpslog.warning("scheduler dropped connection on bad request: %r", e)
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._conn_ids.pop(conn, None)
                self._recovered_conns.discard(conn)

    def _merge_metric_delta(self, conn, payload: bytes) -> None:
        """Fold one node's heartbeat-piggybacked metric delta into the
        cluster aggregate.  Unregistered/unknown senders merge unlabeled;
        a malformed payload is dropped — metrics must never take down the
        control plane."""
        try:
            delta = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(delta, dict):
            return
        with self._lock:
            ident = self._conn_ids.get(conn)
        labels = (
            {"role": ident[0], "rank": str(ident[1])} if ident else None
        )
        # flight-ledger tail: route to the cluster step matrix (it is
        # not a metric delta; merge_delta would ignore it)
        tail = delta.pop("fr", None)
        if tail and ident:
            try:
                self.flight.merge(ident[0], ident[1], tail)
            except Exception as e:  # noqa: BLE001
                from byteps_tpu.common import logging as bpslog

                bpslog.warning("flight tail merge failed: %r", e)
        # server hot-key report → the autotuner's rebalance input
        # (docs/autotune.md); dropped when the tuner is off (a stale
        # server may keep shipping for a beat after a toggle)
        hot = delta.pop("hot", None)
        if hot and ident and ident[0] == "server" and self.tuner is not None:
            self.tuner.note_hot(ident[1], hot)
        # uploaded flight bundles → fleet-central storage
        fb = delta.pop("fb", None)
        if fb and ident:
            try:
                self._store_uploaded_bundles(ident, fb)
            except Exception as e:  # noqa: BLE001
                from byteps_tpu.common import logging as bpslog

                bpslog.warning("flight bundle store failed: %r", e)
        try:
            self.metrics_agg.merge_delta(delta, labels=labels)
        except Exception as e:  # noqa: BLE001
            from byteps_tpu.common import logging as bpslog

            bpslog.warning("metric delta merge failed: %r", e)

    def _touch(self, conn) -> None:
        with self._lock:
            ident = self._conn_ids.get(conn)
            if ident is not None:
                self._last_seen[ident] = time.monotonic()

    def liveness(self) -> Dict[str, Dict[int, float]]:
        """Heartbeat ages in seconds per registered node."""
        now = time.monotonic()
        out: Dict[str, Dict[int, float]] = {"worker": {}, "server": {}}
        with self._lock:
            for (role, rank), ts in self._last_seen.items():
                out[role][rank] = now - ts
        return out

    def _handle_register(self, conn, send_lock, msg: Message) -> None:
        info = json.loads(msg.payload.decode())
        role = info["role"]
        # Stable node identity: workers register with host=''/port=0 (they
        # dial out, they don't listen), so rejoin matching MUST key on the
        # uid the node persists across suspend/resume — an address match
        # would alias every worker to the first entry.  Servers without a
        # uid fall back to their (stable) listen address.
        uid = info.get("uid") or f"{info['host']}:{info['port']}"
        # Control-plane recovery (docs/robustness.md): a node that
        # survived a scheduler crash re-REGISTERs carrying its last-known
        # rank plus the membership/map epochs it acted under.  The rank
        # hint keeps identities stable across the rebirth; the epoch
        # reports floor this instance's counters so the first books it
        # emits fence strictly ABOVE anything a live node already saw.
        hint: Optional[int] = None
        if info.get("last_rank") is not None:
            try:
                hint = int(info["last_rank"])
            except (TypeError, ValueError):
                hint = None
            if hint is not None and hint < 0:
                hint = None
        rejoiner = info.get("last_rank") is not None
        # multi-tenant identity (docs/async.md): tenant workers carry
        # their job id + QoS declaration; job 0 is the single-tenant
        # default namespace
        job = int(info.get("job", 0) or 0)
        job_priority = max(1, int(info.get("job_priority", 1) or 1))
        job_quota = max(0.0, float(info.get("job_quota_mbps", 0) or 0))

        def mk_node(rank: int) -> _Node:
            return _Node(
                rank, info["host"], info["port"], conn, send_lock, uid,
                job=job, job_priority=job_priority,
                job_quota_mbps=job_quota,
            )

        # a control-plane RECONNECT (the node's reconnect machine, not a
        # process restart): the client did not tear its runtime down and
        # will NOT run connect()'s re-init barrier — so its conn must not
        # arm the recovered-conn barrier bypass, or its next TRAINING
        # barrier releases unpaired and desyncs it from its peers
        reconnect = bool(info.get("reconnect"))
        rep_epoch = int(info.get("epoch", 0) or 0)
        rep_map = int(info.get("map_epoch", 0) or 0)
        recovery = False
        resized = False
        with self._lock:
            if rep_epoch > self.epoch:
                self.epoch = rep_epoch
            if rep_map > self.map_epoch:
                self.map_epoch = rep_map
            if rejoiner:
                self._rejoin_reports += 1
                # rebirth detected: bound how long the remaining ranks
                # may take to re-register before the partial population
                # is adopted (no-op on a live scheduler — the book is
                # already out)
                self._arm_rejoin_grace_locked()
                # tuner-state reconstruction (docs/autotune.md): before
                # this successor emits its first books, re-adopt the
                # fleet's live tuning (fusion threshold, codec_off,
                # ring overrides) from the survivors' reports — the
                # first book then CONFIRMS the decisions the fleet
                # already runs instead of reverting them and migrating
                # every overridden key home mid-training.  Only during
                # bring-up: a live scheduler's own tuner state is
                # authoritative over any (necessarily stale) report.
                if (self.tuner is not None and not self._addrbook_sent
                        and info.get("tuning")):
                    self.tuner.adopt_rejoin_report(info["tuning"])
                if not self._addrbook_sent and role == "worker" and not job:
                    # the cluster may have been resized since this
                    # scheduler's env was written; the survivors know
                    # the live topology — adopt their expectation.
                    # TENANT workers (job != 0) report their JOB's
                    # worker count, not the fleet's — never adopt it
                    # (docs/async.md: jobs cannot resize the fleet)
                    nw_r, ns_r = info.get("num_workers"), info.get("num_servers")
                    if nw_r:
                        self.num_workers = int(nw_r)
                    if ns_r:
                        self.num_servers = int(ns_r)
            # Elastic world-size change (ReDeclareTensor + resume(num_workers,
            # num_servers), operations.cc:96-119): a worker re-registering
            # with a DIFFERENT expected topology updates the cluster's
            # expectation.  Dead entries are pruned so their ranks free up;
            # live nodes keep their ranks (stable keys depend on it).
            # tenant workers never resize the fleet: their num_workers is
            # the JOB's size (the averaging population), not a topology
            # expectation for the shared servers (docs/async.md)
            nw = info.get("num_workers") if not job else None
            ns = info.get("num_servers") if not job else None
            if self._addrbook_sent and role == "worker" and (
                (nw and int(nw) != self.num_workers)
                or (ns and int(ns) != self.num_servers)
            ):
                for r in ("worker", "server"):
                    self._nodes[r] = [
                        n for n in self._nodes[r] if n.conn in self._conn_ids
                    ]
                if nw and int(nw) != self.num_workers:
                    self.num_workers = int(nw)
                if ns and int(ns) != self.num_servers:
                    # Server elasticity (resume(num_servers=±k), the
                    # reference rewrites DMLC_NUM_SERVER,
                    # common/__init__.py:75-82).  Scale-DOWN: keep the
                    # lowest-ranked servers, tell the dropped ones to shut
                    # down.  Scale-UP: adopt the expectation; address books
                    # are parked until the new server actually registers.
                    self.num_servers = int(ns)
                    keep, dropped = [], []
                    for n in sorted(self._nodes["server"], key=lambda n: n.rank):
                        (keep if n.rank < self.num_servers else dropped).append(n)
                    self._nodes["server"] = keep
                    for n in dropped:
                        self._conn_ids.pop(n.conn, None)
                        if self.reshard:
                            # DRAIN, don't kill: the dropped server must
                            # first migrate its keys to the new owners.
                            # Its drain book is sent from
                            # _complete_recovery, AFTER the map epoch
                            # bump, so it drains against the settled
                            # topology; it stops itself when done.
                            self._pending_drains.append(n)
                            continue
                        try:
                            send_message(
                                n.conn, Message(Op.SHUTDOWN, seq=RESIZE_SEQ),
                                n.send_lock,
                            )
                        except (ConnectionError, OSError):
                            pass
                resized = True
            nodes = self._nodes[role]
            existing = [n for n in nodes if n.uid == uid]
            if existing and self._addrbook_sent:
                node = existing[0]
                rank = node.rank
                # drop the dead connection's identity so its stray bytes
                # can't refresh the rejoined node's liveness stamp
                self._conn_ids.pop(node.conn, None)
                # scrub the dead connection's parked barrier waiters: the
                # rejoiner's barrier() RETRY re-sends on the new conn, and
                # a stale entry would double-count this rank — releasing
                # the barrier without its peers and skewing the round
                # counter (the same hazard eviction scrubs for)
                self._scrub_barrier_waiters_locked(node.conn)
                nodes[nodes.index(node)] = mk_node(rank)
                recovery = True
                if not reconnect:
                    self._recovered_conns.add(conn)
            elif self._addrbook_sent:
                # Unknown uid joining a full cluster: a process-level restart
                # lost its uuid (BYTEPS_NODE_UID unset), or a scale-up added
                # room.  Adopt a dead member's slot when one exists; join at
                # the lowest free rank when the (possibly just-resized)
                # population has room; otherwise REFUSE with an error reply
                # — appending an extra rank would skew barrier group sizes
                # and per-key push counts for the whole cluster, and
                # silence would leave the registrant hanging.
                dead = [n for n in nodes if n.conn not in self._conn_ids]
                expected = self.num_workers if role == "worker" else self.num_servers
                if dead:
                    node = dead[0]
                    rank = node.rank
                    nodes[nodes.index(node)] = _Node(
                        rank, info["host"], info["port"], conn, send_lock, uid
                    )
                    # the slot's IDENTITY changed (new uid, and for a
                    # server a new address) — surviving peers must hear
                    # about it or they keep dialing the dead member's
                    # address; piggyback the membership-epoch broadcast
                    # on the adoption (see _complete_recovery)
                    resized = True
                elif len(nodes) < expected:
                    used = {n.rank for n in nodes}
                    rank = next(r for r in range(expected) if r not in used)
                    nodes.append(mk_node(rank))
                    # the live rank set GREW: peers (and especially the
                    # servers' zombie fence) must learn the new member's
                    # rank is legitimate — broadcast like an adoption
                    resized = True
                elif hint is not None and hint not in {n.rank for n in nodes}:
                    # late reconnector arriving AFTER a rejoin-window
                    # partial adoption shrank the expectation: its rank
                    # is provably unclaimed, so grow the expectation
                    # back and re-admit it rather than refusing a member
                    # that merely reconnected slowly
                    rank = hint
                    nodes.append(mk_node(rank))
                    if role == "worker":
                        self.num_workers += 1
                    else:
                        self.num_servers += 1
                    resized = True
                else:
                    err = {
                        "error": f"cluster full: no dead {role} slot to adopt; "
                        "set BYTEPS_NODE_UID to rejoin as a known member"
                    }
                    try:
                        send_message(
                            conn,
                            Message(
                                Op.ADDRBOOK,
                                status=1,
                                seq=msg.seq,
                                payload=json.dumps(err).encode(),
                            ),
                            send_lock,
                        )
                    except (ConnectionError, OSError):
                        pass
                    return
                recovery = True  # mid-training join: immediate book +
                if not reconnect:  # barrier bypass (restarts only)
                    self._recovered_conns.add(conn)
            elif existing:
                # same uid RE-registering during the initial fill: its
                # first REGISTER's reply is parked (population short) and
                # that conn died, so the reconnect machine redialed.
                # REPLACE the entry — appending would create a ghost that
                # steals the node's own rank hint, inflates the
                # population count (tripping `full`/the grace adoption
                # early), and swallows one of the first books on a dead
                # socket.
                node = existing[0]
                rank = node.rank
                self._conn_ids.pop(node.conn, None)
                self._scrub_barrier_waiters_locked(node.conn)
                nodes[nodes.index(node)] = mk_node(rank)
            else:
                # initial fill.  A rejoiner's rank hint is honored when
                # free (rank-stable rebirth: keys, ledgers, and barrier
                # group sizing all depend on stable rank identities);
                # fresh first-boot registrants carry no hint and keep
                # the arrival-order assignment.
                used = {n.rank for n in nodes}
                if hint is not None and hint not in used:
                    rank = hint
                else:
                    rank = next(
                        r for r in range(len(nodes) + 1) if r not in used
                    )
                nodes.append(mk_node(rank))
            self._conn_ids[conn] = (role, rank)
            self._last_seen[(role, rank)] = time.monotonic()
            full = (
                len(self._nodes["worker"]) >= self.num_workers
                and len(self._nodes["server"]) >= self.num_servers
            )
            if recovery:
                self._complete_recovery(conn, send_lock, role, rank, msg.seq, resized)
                return
            if full and not self._addrbook_sent:
                self._emit_initial_books_locked()

    def _emit_initial_books_locked(self) -> None:
        """Ship this incarnation's first address books (population
        complete, or the rejoin grace window adopted a partial one).
        Caller holds the lock.

        A REBORN scheduler — any registrant reported a prior incarnation
        — fences both epochs strictly above everything reported (the
        counters were floored to the maxima at registration; the bumps
        land above them), so a zombie's last book can never outrank the
        successor's first.  Liveness stamps are refreshed at emission:
        nodes cannot heartbeat while their registration is parked, and
        with a rejoin window longer than BYTEPS_DEAD_NODE_TIMEOUT_S the
        stale stamps would otherwise mass-evict the whole fleet the
        moment eviction re-arms."""
        self._addrbook_sent = True
        recovery = self._rejoin_reports > 0
        if recovery:
            self.epoch += 1
        self._bump_map_epoch_locked()  # initial placement: above any report
        now = time.monotonic()
        for r in ("worker", "server"):
            for node in self._nodes[r]:
                self._last_seen[(r, node.rank)] = now
                self._send_addrbook_to(
                    node.conn, node.send_lock, r, node.rank, 0,
                    recovery=recovery,
                )

    def _arm_rejoin_grace_locked(self) -> None:
        """Start the rebirth grace timer (once): when it expires before
        the full previously-reported population returned, the present
        subset is adopted as the truth.  Caller holds the lock."""
        if (self._grace_thread is not None or self._addrbook_sent
                or self.rejoin_window <= 0):
            return
        deadline = time.monotonic() + self.rejoin_window

        def _expire() -> None:
            while not self._stop.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                if self._stop.wait(min(remaining, 0.2)):
                    return
            self._adopt_partial_population()

        self._grace_thread = threading.Thread(
            target=_expire, name="sched-rejoin-grace", daemon=True
        )
        self._grace_thread.start()

    def _adopt_partial_population(self) -> None:
        """Rejoin window expired with ranks still missing: adopt the
        re-registered subset as the new expected population and emit
        books — the alternative is stranding every survivor forever on
        a member that died with (or during) the old scheduler.  A
        missing rank that reconnects later is re-admitted (expectation
        grows back; see the late-reconnector branch in
        ``_handle_register``)."""
        with self._lock:
            if self._addrbook_sent or self._stop.is_set():
                return
            nw = len(self._nodes["worker"])
            ns = len(self._nodes["server"])
            if nw + ns == 0:
                return  # nobody rejoined; nothing to adopt
            from byteps_tpu.common import logging as bpslog

            bpslog.warning(
                "rejoin window (%.1fs) expired with %d/%d workers and "
                "%d/%d servers re-registered — adopting the partial "
                "population", self.rejoin_window, nw, self.num_workers,
                ns, self.num_servers,
            )
            self.num_workers = nw
            self.num_servers = ns
            self._emit_initial_books_locked()

    def _complete_recovery(self, conn, send_lock, role, rank, seq, resized) -> None:
        """Reply to a mid-training (re)registration — parking worker
        replies while a server scale-up leaves the population short, and
        broadcasting RESIZE_SEQ books to the rest of the cluster once the
        topology settles.  Caller holds ``self._lock``."""
        servers_ready = len(self._nodes["server"]) >= self.num_servers
        if role == "worker" and not servers_ready:
            # the book this worker needs doesn't exist yet (it would list
            # fewer servers than the topology it just declared); its
            # connect() blocks until the new server registers
            self._parked_regs.append((conn, send_lock, role, rank, seq))
            self._pending_broadcast = self._pending_broadcast or resized
            return
        if resized or self._parked_regs or self._pending_broadcast:
            # topology-visible change (resize, dead-slot adoption, parked
            # flush): new membership epoch — stamp it into EVERY book sent
            # below, the recovering node's included.  The OWNERSHIP epoch
            # advances only when the server set itself changed.
            self.epoch += 1
            self._bump_map_epoch_locked()
        self._send_addrbook_to(conn, send_lock, role, rank, seq, recovery=True)
        parked, self._parked_regs = self._parked_regs, []
        for pconn, plock, prole, prank, pseq in parked:
            self._send_addrbook_to(pconn, plock, prole, prank, pseq, recovery=True)
        if resized or parked or self._pending_broadcast:
            self._pending_broadcast = False
            # every OTHER live node adopts the new topology from an
            # unsolicited RESIZE_SEQ book on its control connection
            exclude = {conn} | {p[0] for p in parked}
            for r in ("worker", "server"):
                for node in self._nodes[r]:
                    if node.conn not in exclude:
                        self._send_addrbook_to(
                            node.conn, node.send_lock, r, node.rank, RESIZE_SEQ
                        )
        # scale-down under resharding: each dropped server gets a DRAIN
        # book (the new topology, its own rank excluded, drain flag set)
        # so it migrates every key it owns to the new owners and then
        # stops itself — the SHUTDOWN-cold path is the legacy behavior
        drains, self._pending_drains = self._pending_drains, []
        for n in drains:
            self._send_addrbook_to(
                n.conn, n.send_lock, "server", n.rank, RESIZE_SEQ, drain=True
            )

    def _send_addrbook_to(self, conn, send_lock, role, rank, seq,
                          recovery=False, drain=False) -> None:
        servers = sorted(self._nodes["server"], key=lambda n: n.rank)
        book = {
            "role": role,
            "rank": rank,
            "num_workers": self.num_workers,
            # during a scale-up a new server can register before the
            # resize-initiating worker: the book then already lists it, so
            # num_servers must never undercount the list it ships with
            "num_servers": max(self.num_servers, len(servers)),
            "servers": [(n.host, n.port) for n in servers],
            "is_recovery": recovery,
            # membership observability (docs/robustness.md): receivers
            # track the epoch and mirror eviction totals into telemetry;
            # servers use the live worker-rank list as the zombie fence
            # (pushes from evicted ranks are rejected)
            "epoch": self.epoch,
            "evictions": dict(self.eviction_totals),
            "worker_ranks": sorted(n.rank for n in self._nodes["worker"]),
            # ownership plane (docs/robustness.md "migration flow"):
            # server RANKS parallel to the address list (ranks are stable
            # identities — after an eviction the list is non-contiguous),
            # plus the map epoch those ranks own the key space under.
            # "drain": this book orders the receiving server to migrate
            # every key out and stop (it is no longer in the rank list).
            "server_ranks": [n.rank for n in servers],
            "map_epoch": self.map_epoch,
            # zombie-scheduler fence (docs/robustness.md "Control-plane
            # recovery"): nodes track the highest incarnation seen and
            # refuse books stamped with an older one
            "sched_incarnation": self.incarnation,
            # multi-tenant membership + QoS map (docs/async.md): which
            # worker ranks belong to which job, plus the job's weighted
            # share and admission quota.  Workers aggregate over their
            # OWN job's population; servers size per-key rounds/barriers
            # per job and weight/meter service accordingly.
            "jobs": self._jobs_map_locked(),
        }
        if self.tuner is not None:
            # adaptive control plane (docs/autotune.md): the versioned
            # ``tuning`` section + any live ``ring_overrides``, filtered
            # to this book's own rank list.  With the tuner off the book
            # is byte-for-byte the legacy shape.
            book.update(self.tuner.book_extras(book["server_ranks"]))
        if drain:
            book["drain"] = True
        try:
            send_message(
                conn,
                Message(Op.ADDRBOOK, payload=json.dumps(book).encode(), seq=seq),
                send_lock,
            )
        except (ConnectionError, OSError):
            pass

    def _jobs_map_locked(self) -> Dict[str, dict]:
        """``{job: {"workers": [ranks], "priority": w, "quota_mbps": q}}``
        from the live worker registrations.  Priority/quota take the MAX
        any of the job's workers declared (one straggling env var must
        not silently zero a job's share)."""
        jobs: Dict[str, dict] = {}
        for n in self._nodes["worker"]:
            j = jobs.setdefault(
                str(n.job),
                {"workers": [], "priority": 1, "quota_mbps": 0.0},
            )
            j["workers"].append(n.rank)
            j["priority"] = max(j["priority"], n.job_priority)
            j["quota_mbps"] = max(j["quota_mbps"], n.job_quota_mbps)
        ns = max(1, len(self._nodes["server"]))
        for j in jobs.values():
            j["workers"].sort()
            if j["quota_mbps"] > 0:
                # fleet-coordinated admission (docs/async.md): the
                # declared BYTEPS_JOB_QUOTA_MBPS is the job's FLEET-wide
                # budget — each server enforces an equal share, so the
                # aggregate cap equals the declaration instead of
                # quota × servers.  Re-divided automatically: this map
                # is rebuilt into every book a server-set change ships.
                j["quota_mbps_total"] = j["quota_mbps"]
                j["quota_mbps"] = j["quota_mbps"] / ns
        return jobs

    def _group_size(self, group: int) -> int:
        return {
            GROUP_WORKERS: self.num_workers,
            GROUP_SERVERS: self.num_servers,
            GROUP_ALL: self.num_workers + self.num_servers,
        }[group]

    def _handle_barrier(self, conn, send_lock, msg: Message) -> None:
        group = msg.flags or GROUP_ALL
        with self._lock:
            if conn in self._recovered_conns:
                # recovering node's re-init barrier: release immediately —
                # no other node is at a barrier to pair with
                self._recovered_conns.discard(conn)
                try:
                    send_message(conn, Message(Op.BARRIER, seq=msg.seq, flags=group), send_lock)
                except (ConnectionError, OSError):
                    pass
                return
        with self._lock:
            rnd = self._barrier_round[group]
            waiters = self._barriers.setdefault((group, rnd), [])
            waiters.append((conn, send_lock, msg.seq))
            if len(waiters) >= self._group_size(group):
                self._barrier_round[group] = rnd + 1
                del self._barriers[(group, rnd)]
                for wconn, wlock, wseq in waiters:
                    try:
                        send_message(wconn, Message(Op.BARRIER, seq=wseq, flags=group), wlock)
                    except (ConnectionError, OSError):
                        pass
