"""Shared retry machinery for the self-healing data plane.

One backoff policy serves every layer that re-attempts network work:

- van ``connect()`` smoothing over cluster bring-up races (a worker
  dialing a scheduler/server that has not bound its port yet),
- per-RPC retry in :mod:`byteps_tpu.comm.ps_client` (deadline expiry,
  dropped frames, injected disconnects from the chaos van),
- the PS client's dead-connection revival.

Exponential backoff with full jitter (the AWS-architecture result: under
contention, jittered backoff drains a thundering herd an order of
magnitude faster than synchronized retries) — delay for attempt ``k`` is
uniform in ``(0, min(cap, base * 2**k)]``.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type


class Backoff:
    """Exponential backoff schedule with full jitter.

    ``rng`` is injectable so chaos tests can pin the schedule; the
    default uses a private ``random.Random()`` (never the global seed —
    training code may have seeded it for data order).
    """

    def __init__(
        self,
        base: float = 0.1,
        cap: float = 2.0,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.base = max(1e-4, base)
        self.cap = cap
        self._rng = rng or random.Random()
        self.attempt = 0

    def next_delay(self) -> float:
        """Delay to sleep before the NEXT attempt (advances the schedule)."""
        ceiling = min(self.cap, self.base * (2 ** self.attempt))
        self.attempt += 1
        # full jitter, but never 0: a zero sleep turns a dead-connection
        # retry loop into a busy spin
        delay = ceiling * (0.1 + 0.9 * self._rng.random())
        # every layer that backs off (per-RPC retry, revival, van dials)
        # feeds one latency distribution: the "how long do we sit out
        # waiting to retry" signal (docs/observability.md)
        from byteps_tpu.core.telemetry import metrics

        metrics().observe("retry_backoff_seconds", delay)
        return delay

    def reset(self) -> None:
        self.attempt = 0


def call_with_retries(
    fn: Callable,
    budget_s: float,
    retry_on: Tuple[Type[BaseException], ...],
    base: float = 0.05,
    cap: float = 1.0,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` until it succeeds or ``budget_s`` of wall time is
    spent; re-raises the last error once the budget is exhausted.  Only
    exceptions in ``retry_on`` are retried — anything else propagates
    immediately (a refused connection is transient; a bad address is not).
    """
    deadline = time.monotonic() + max(0.0, budget_s)
    bo = Backoff(base=base, cap=cap)
    while True:
        try:
            return fn()
        except retry_on:
            delay = bo.next_delay()
            if time.monotonic() + delay >= deadline:
                raise
            sleep(delay)
