"""Test-only link shaping for the PS data plane.

The PS design exists for the DCN regime — links with real propagation
delay and finite bandwidth (reference rationale: docs/rationale.md,
"inter-machine bandwidth is the bottleneck") — but every test in this
environment runs on loopback, where sends complete in microseconds and
any queueing discipline looks the same.  These knobs let loopback
emulate a DCN link so scheduling/overlap effects become measurable:

- ``BYTEPS_VAN_DELAY_MS``   — one-way propagation delay added per
  message (pipelined: it delays delivery, it does not occupy the wire).
- ``BYTEPS_VAN_RATE_MBYTES_S`` — link bandwidth in **megabytes per
  second**; serialization time ``bytes/rate`` occupies the virtual
  wire, so back-to-back messages queue behind each other exactly like
  a real NIC.  (``BYTEPS_VAN_RATE_MBPS`` is the deprecated original
  spelling of the same knob — it always meant MB/s despite the
  "mbps" suffix, the naming trap this rename closes; it still works,
  with a one-time warning, and the canonical name wins when both are
  set.)
- ``BYTEPS_VAN_SHAPE_BUF_KB`` — shaping buffer (default 256): once this
  many bytes are queued on the virtual wire, ``sendall`` blocks.  This
  is the kernel-socket-buffer analogue that propagates backpressure to
  the engine's PUSH stage — without it every gradient would "send"
  instantly and the scheduler's pop order could never matter.

Model per connection (one virtual wire each way):

    arrival = max(enqueue_time, wire_free) + bytes/rate + delay

The delivery thread preserves FIFO order per connection — shaping never
reorders; only the *sender's* queueing discipline (the scheduler under
test) decides order.

Shaping wraps only data-plane sockets (worker<->server); the scheduler
control plane stays unshaped.  Payload bytes are copied at ``sendall``
time: the engine's zero-copy staging buffers are reused after
``send_message`` returns, and a shaped send outlives that return by
design.  That copy is why this is a test knob, not a production path.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Optional


_warned_legacy_rate = False


def _rate_mbytes_s() -> float:
    """Link bandwidth in MB/s: canonical ``BYTEPS_VAN_RATE_MBYTES_S``,
    falling back to the deprecated ``BYTEPS_VAN_RATE_MBPS`` alias (same
    unit — it was always megaBYTES/s despite the name) with a one-time
    warning.  The canonical spelling wins when both are set."""
    v = os.environ.get("BYTEPS_VAN_RATE_MBYTES_S")
    if v not in (None, ""):
        return float(v)
    legacy = os.environ.get("BYTEPS_VAN_RATE_MBPS")
    if legacy in (None, ""):
        return 0.0
    global _warned_legacy_rate
    if not _warned_legacy_rate:
        _warned_legacy_rate = True
        from byteps_tpu.common import logging as bps_logging

        bps_logging.warning(
            "BYTEPS_VAN_RATE_MBPS is deprecated (the unit is megaBYTES/s, "
            "not megabits) — use BYTEPS_VAN_RATE_MBYTES_S; honoring the "
            "old name with the same MB/s meaning",
        )
    return float(legacy)


def shaping_params() -> tuple:
    """(delay_s, rate_Bps, buf_bytes) from env; (0, 0, _) means off."""
    delay_ms = float(os.environ.get("BYTEPS_VAN_DELAY_MS", "0") or 0)
    rate_mbytes_s = _rate_mbytes_s()
    buf_kb = float(os.environ.get("BYTEPS_VAN_SHAPE_BUF_KB", "256") or 256)
    return delay_ms / 1e3, rate_mbytes_s * 1e6, max(1, int(buf_kb * 1024))


def shaping_enabled() -> bool:
    delay_s, rate_bps, _ = shaping_params()
    return delay_s > 0 or rate_bps > 0


class ShapedSocket:
    """Socket proxy whose sends traverse a virtual shaped link.

    ``sendall`` copies the data, enqueues it, and blocks only on the
    shaping buffer; a delivery thread serializes the queue onto the real
    socket at the configured rate + delay.  Receives, timeouts, and
    teardown pass straight through.  Deliberately does NOT expose
    ``sendmsg`` so transport._send falls back to plain ``sendall``.
    """

    def __init__(self, sock: socket.socket, delay_s: float, rate_bps: float,
                 buf_bytes: int) -> None:
        self._sock = sock
        self._delay = delay_s
        self._rate = rate_bps
        self._buf_limit = buf_bytes
        self._queue: deque = deque()        # (data, deliver_at)
        self._inflight: deque = deque()     # (nbytes, serialized_at)
        self._queued_bytes = 0
        self._wire_free = 0.0               # virtual wire clock (lock-guarded)
        self._lock = threading.Lock()
        self._can_send = threading.Condition(self._lock)
        self._can_deliver = threading.Condition(self._lock)
        self._closed = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._delivery_loop, name="van-shaper", daemon=True
        )
        self._thread.start()

    # --- sender side ------------------------------------------------------
    def _reap_serialized(self, now: float) -> Optional[float]:
        """Release buffer bytes whose virtual serialization time has
        passed (they are "on the wire"); returns the next release time.
        Caller holds the lock.  Propagation delay deliberately does NOT
        hold buffer space — otherwise sustained throughput would cap at
        buf_bytes/delay instead of the configured rate."""
        while self._inflight and self._inflight[0][1] <= now:
            nbytes, _ = self._inflight.popleft()
            self._queued_bytes -= nbytes
        return self._inflight[0][1] if self._inflight else None

    def sendall(self, data) -> None:
        data = bytes(data)  # staging buffers are reused after return
        with self._lock:
            while True:
                if self._error is not None:
                    raise ConnectionError(f"shaped link dead: {self._error!r}")
                if self._closed:
                    raise ConnectionError("shaped link closed")
                now = time.monotonic()
                next_release = self._reap_serialized(now)
                if (self._queued_bytes + len(data) <= self._buf_limit
                        or self._queued_bytes == 0):
                    break
                timeout = 1.0
                if next_release is not None:
                    timeout = min(timeout, max(next_release - now, 0.0) + 1e-4)
                self._can_send.wait(timeout=timeout)
            # virtual wire times are fixed at ENQUEUE: the delivery
            # thread's position (which includes propagation sleeps) must
            # never slow the serialization clock
            start = max(now, self._wire_free)
            tx = (len(data) / self._rate) if self._rate > 0 else 0.0
            self._wire_free = start + tx
            self._queue.append((data, self._wire_free + self._delay))
            self._inflight.append((len(data), self._wire_free))
            self._queued_bytes += len(data)
            self._can_deliver.notify()

    def _delivery_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._can_deliver.wait(timeout=1.0)
                if self._closed and not self._queue:
                    return
                data, deliver_at = self._queue.popleft()
            # absolute deadline: back-to-back messages' propagation
            # delays overlap (pipelined, not cumulative)
            wait = deliver_at - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            try:
                self._sock.sendall(data)
            except BaseException as e:  # noqa: BLE001 — surface to senders
                with self._lock:
                    self._error = e
                    self._queue.clear()
                    self._inflight.clear()
                    self._queued_bytes = 0
                    self._can_send.notify_all()
                return

    # --- passthrough ------------------------------------------------------
    @property
    def family(self):
        return self._sock.family

    def recv(self, n: int) -> bytes:
        return self._sock.recv(n)

    def recv_into(self, buf, nbytes: int = 0) -> int:
        return self._sock.recv_into(buf, nbytes)

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def setsockopt(self, *a) -> None:
        self._sock.setsockopt(*a)

    def fileno(self) -> int:
        return self._sock.fileno()

    def shutdown(self, how: int = socket.SHUT_RDWR) -> None:
        # teardown path: queued-but-undelivered data is dropped, exactly
        # like un-flushed kernel buffers on a hard shutdown
        with self._lock:
            self._closed = True
            self._queue.clear()
            self._inflight.clear()
            self._queued_bytes = 0
            self._can_deliver.notify_all()
            self._can_send.notify_all()
        try:
            self._sock.shutdown(how)
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._queue.clear()
            self._inflight.clear()
            self._queued_bytes = 0
            self._can_deliver.notify_all()
            self._can_send.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


_warned_native = set()


def warn_native_bypass_once(context: str) -> None:
    """One warning per process per context when a native (C++) data
    plane is disabled/bypassed because shaping is on — the C++ lanes
    would silently skip the shaper and report an unshaped link as
    shaped."""
    if context in _warned_native:
        return
    _warned_native.add(context)
    from byteps_tpu.common import logging as bps_logging

    bps_logging.warning(
        "BYTEPS_VAN_DELAY_MS/RATE_MBYTES_S set: %s (shaping needs the "
        "Python data plane)", context,
    )


def maybe_shape(sock):
    """Wrap a data-plane socket in the shaped link if env enables it.

    Applied on BOTH ends of a connection (worker connect + server
    accept), giving each direction its own independent virtual wire —
    a full-duplex link, like the real thing.
    """
    delay_s, rate_bps, buf_bytes = shaping_params()
    if delay_s <= 0 and rate_bps <= 0:
        return sock
    if not isinstance(sock, socket.socket):
        return sock  # shm van rings: shaping targets the fd-stream vans
    return ShapedSocket(sock, delay_s, rate_bps, buf_bytes)
