"""Shared-memory SPSC byte ring — the data plane of the ``shm`` van.

The reference stages all local traffic through POSIX shared memory
(``BytePS_ShM_<key>`` buffers, shared_memory.cc:28-50) and its ps-lite
layer exists precisely to move bulk payloads without extra copies
(zero-copy ZPush/ZPull, core_loops.cc:538-618).  For same-host
worker↔server traffic the TPU build gets the same property from one
mmap'd ring per direction: the producer memcpys payload bytes straight
into shared memory and the consumer memcpys them out — no kernel socket
buffers, no syscalls on the bulk path, no per-message allocations in
between.  This is the "RDMA-class" seam proof for the van interface:
a transport whose payload never crosses a socket.

Layout of the mapped file (created in ``/dev/shm`` so the pages are
tmpfs-backed, mirroring the reference's ``shm_open``):

    u64 head    @ 0   total bytes ever written (producer-owned)
    u64 tail    @ 8   total bytes ever read (consumer-owned)
    u8  closed  @ 16  either side sets 1 to tear down
    u8  rd_park @ 17  consumer is parked waiting for data (doorbell me)
    u8  wr_park @ 18  producer is parked waiting for space (doorbell me)
    pad to 64B        (cache-line separation of the counters)
    data        @ 64  capacity = file size − 64

Single producer, single consumer (the van serializes senders with the
connection lock).  Counters are monotonically increasing 8-byte aligned
stores: on x86-64's TSO memory model the data-then-head publication
order is preserved without fences, which is the same contract the
reference's lock-free queues rely on.

Stall handoff is doorbell-driven (virtio-style suppressed
notifications): a side that finds the ring empty/full spins briefly,
then sets its park flag and sleeps in select() on the van's CONTROL
socket; the peer, after publishing a counter, checks the flag and —
only when someone is parked — writes one doorbell byte to the control
socket, waking the sleeper instantly.  The bulk path stays
syscall-free; the park timeout (``_PARK_S``) is the backstop for two
lossy cases, each costing one park tick, never a hang: (a) the TSO
store→load race where both sides miss each other (producer:
publish-then-read-flag; parker: set-flag-then-recheck — x86 allows
both to see stale values), and (b) doorbell steal — both directions
share one control socket, so when a process has a reader AND a writer
parked at once, whichever drains the socket first can swallow the
other's wakeup byte.
"""

from __future__ import annotations

import mmap
import os
import tempfile
import time
import uuid

_HDR = 64
#: park backstop: lost-doorbell worst case latency; 20Hz idle wake rate
_PARK_S = 0.05
#: brief pre-park spin: cheap for back-to-back traffic, avoids flag churn
_SPINS = 10


def _shm_dir() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def create_ring_file(size: int, tag: str = "") -> str:
    """Allocate a ring backing file; returns its path (the wire name)."""
    path = os.path.join(
        _shm_dir(), f"byteps_ring_{tag}{os.getpid()}_{uuid.uuid4().hex[:8]}"
    )
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    try:
        os.ftruncate(fd, _HDR + size)
    finally:
        os.close(fd)
    return path


class ShmRing:
    """One direction of a connection.  ``role`` is "producer" or
    "consumer"; both attach to the same file."""

    def __init__(self, path: str, role: str, unlink: bool = False) -> None:
        assert role in ("producer", "consumer")
        self.path = path
        self.role = role
        self._unlink = unlink
        fd = os.open(path, os.O_RDWR)
        try:
            total = os.fstat(fd).st_size
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self.capacity = total - _HDR
        self._view = memoryview(self._mm)
        # Counter access MUST be a single 8-byte load/store: CPython's
        # struct pack_into with a standard ('<Q') format writes the value
        # BYTE BY BYTE, so a cross-process reader (incl. the C++ engine's
        # atomic loads) can observe a torn intermediate counter, compute a
        # wildly inflated avail/free, and run the ring off its own data
        # (found as BAD MAGIC / zero-header desyncs under multi-worker
        # load).  A native-format ('Q') cast memoryview stores via one
        # 8-byte memcpy — a single aligned mov on x86-64, which the shm
        # van already requires (little-endian, TSO).
        self._ctr = self._view[:16].cast("Q")  # [0]=head, [1]=tail
        #: van-provided doorbell: one byte on the control socket to wake a
        #: parked peer; None = fall back to sleep-polling (tests)
        self.kick = None

    # -- counter accessors ------------------------------------------------
    def _head(self) -> int:
        return self._ctr[0]

    def _tail(self) -> int:
        return self._ctr[1]

    def _closed(self) -> bool:
        return self._mm[16] != 0

    def mark_closed(self) -> None:
        try:
            self._mm[16] = 1
        except ValueError:  # already unmapped
            pass

    def _peer_parked(self, flag_off: int) -> bool:
        try:
            return self._mm[flag_off] != 0
        except ValueError:
            return False

    def _set_park(self, flag_off: int, value: int) -> None:
        try:
            self._mm[flag_off] = value
        except ValueError:
            pass

    def _kick_peer(self, flag_off: int) -> None:
        """Doorbell the peer if (and only if) it declared itself parked —
        the common no-contention case stays syscall-free."""
        if self.kick is not None and self._peer_parked(flag_off):
            self.kick()

    def _stall(self, flag_off: int, parked: bool, stalls: int, wait):
        """One step of the park protocol shared by both ring directions:
        spin (yield the CPU — producer and consumer may share a core),
        then declare the park flag and recheck once, then sleep on the
        control socket.  Returns (parked, alive); alive=False means the
        wait saw the peer die."""
        if stalls <= _SPINS:
            os.sched_yield()
            return parked, True
        if not parked:
            # park: declare it, RECHECK (the peer kicks only if it saw
            # the flag), then sleep on the control socket
            self._set_park(flag_off, 1)
            return True, True
        if wait is not None:
            return parked, wait(_PARK_S)
        time.sleep(_PARK_S)
        return parked, True

    # -- producer side ----------------------------------------------------
    def write(self, data, wait=None) -> None:
        """Block until all of ``data`` is in the ring (socket sendall
        semantics).  Raises ConnectionError if the peer closed.
        ``wait(timeout) -> bool`` replaces the stall sleep when given;
        returning False means the peer died without setting the closed
        flag (e.g. SIGKILL) — the van passes a select() on its control
        socket so death wakes the wait instantly."""
        src = memoryview(data)
        if src.nbytes and src.format != "B":
            src = src.cast("B")
        off = 0
        n = src.nbytes
        stalls = 0
        parked = False
        try:
            while off < n:
                try:
                    head, tail = self._head(), self._tail()
                except ValueError:  # our own side already closed/unmapped
                    raise ConnectionError("shm ring closed") from None
                free = self.capacity - (head - tail)
                if free == 0:
                    if self._closed():
                        raise ConnectionError("shm ring peer closed")
                    stalls += 1
                    parked, alive = self._stall(18, parked, stalls, wait)
                    if not alive:
                        raise ConnectionError("shm ring peer closed")
                    continue
                if parked:
                    parked = False
                    self._set_park(18, 0)
                stalls = 0
                pos = head % self.capacity
                chunk = min(free, n - off, self.capacity - pos)
                try:
                    self._view[_HDR + pos : _HDR + pos + chunk] = src[off : off + chunk]
                    # publish AFTER the payload bytes are in place
                    self._ctr[0] = head + chunk
                except ValueError:
                    raise ConnectionError("shm ring closed") from None
                off += chunk
                self._kick_peer(17)  # wake a parked consumer
        finally:
            if parked:
                self._set_park(18, 0)
        if self._closed():
            raise ConnectionError("shm ring peer closed")

    # -- consumer side ----------------------------------------------------
    def recv_into(self, buf, nbytes: int = 0, wait=None) -> int:
        """Socket recv_into semantics: block until ≥1 byte, copy up to
        ``nbytes`` (or len(buf)), return count; 0 once closed+drained.
        ``wait`` as in :meth:`write`."""
        dst = memoryview(buf)
        if dst.nbytes and dst.format != "B":
            dst = dst.cast("B")
        want = nbytes or dst.nbytes
        stalls = 0
        dead = False
        parked = False
        try:
            while True:
                try:
                    head, tail = self._head(), self._tail()
                except ValueError:  # our own side already closed/unmapped
                    return 0
                avail = head - tail
                if avail:
                    if parked:
                        parked = False
                        self._set_park(17, 0)
                    pos = tail % self.capacity
                    chunk = min(avail, want, self.capacity - pos)
                    try:
                        dst[:chunk] = self._view[_HDR + pos : _HDR + pos + chunk]
                        self._ctr[1] = tail + chunk
                    except ValueError:
                        return 0
                    self._kick_peer(18)  # wake a producer parked on full
                    return chunk
                if dead:
                    return 0
                if self._closed():
                    dead = True  # drain once more: a final response may
                    continue     # have landed just before the peer exited
                stalls += 1
                parked, alive = self._stall(17, parked, stalls, wait)
                if not alive:
                    dead = True
        finally:
            if parked:
                self._set_park(17, 0)

    def close(self) -> None:
        self.mark_closed()
        try:
            self._ctr.release()
            self._view.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass
        if self._unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass
