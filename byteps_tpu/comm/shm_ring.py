"""Shared-memory SPSC byte ring — the data plane of the ``shm`` van.

The reference stages all local traffic through POSIX shared memory
(``BytePS_ShM_<key>`` buffers, shared_memory.cc:28-50) and its ps-lite
layer exists precisely to move bulk payloads without extra copies
(zero-copy ZPush/ZPull, core_loops.cc:538-618).  For same-host
worker↔server traffic the TPU build gets the same property from one
mmap'd ring per direction: the producer memcpys payload bytes straight
into shared memory and the consumer memcpys them out — no kernel socket
buffers, no syscalls on the bulk path, no per-message allocations in
between.  This is the "RDMA-class" seam proof for the van interface:
a transport whose payload never crosses a socket.

Layout of the mapped file (created in ``/dev/shm`` so the pages are
tmpfs-backed, mirroring the reference's ``shm_open``):

    u64 head    @ 0   total bytes ever written (producer-owned)
    u64 tail    @ 8   total bytes ever read (consumer-owned)
    u8  closed  @ 16  either side sets 1 to tear down
    pad to 64B        (cache-line separation of the counters)
    data        @ 64  capacity = file size − 64

Single producer, single consumer (the van serializes senders with the
connection lock).  Counters are monotonically increasing 8-byte aligned
stores: on x86-64's TSO memory model the data-then-head publication
order is preserved without fences, which is the same contract the
reference's lock-free queues rely on.
"""

from __future__ import annotations

import mmap
import os
import tempfile
import time
import uuid

_HDR = 64


def _shm_dir() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def _stall_cap(stalls: int) -> float:
    """Backoff ceiling for ring waits: 1ms while traffic is recent (first
    message after a pause pays ≤1ms), 10ms once the connection has been
    idle a while (~100 stalls) so parked reader threads wake at ~100Hz,
    not ~1kHz, per idle connection."""
    return 1e-2 if stalls > 100 else 1e-3


def create_ring_file(size: int, tag: str = "") -> str:
    """Allocate a ring backing file; returns its path (the wire name)."""
    path = os.path.join(
        _shm_dir(), f"byteps_ring_{tag}{os.getpid()}_{uuid.uuid4().hex[:8]}"
    )
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
    try:
        os.ftruncate(fd, _HDR + size)
    finally:
        os.close(fd)
    return path


class ShmRing:
    """One direction of a connection.  ``role`` is "producer" or
    "consumer"; both attach to the same file."""

    def __init__(self, path: str, role: str, unlink: bool = False) -> None:
        assert role in ("producer", "consumer")
        self.path = path
        self.role = role
        self._unlink = unlink
        fd = os.open(path, os.O_RDWR)
        try:
            total = os.fstat(fd).st_size
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self.capacity = total - _HDR
        self._view = memoryview(self._mm)
        # Counter access MUST be a single 8-byte load/store: CPython's
        # struct pack_into with a standard ('<Q') format writes the value
        # BYTE BY BYTE, so a cross-process reader (incl. the C++ engine's
        # atomic loads) can observe a torn intermediate counter, compute a
        # wildly inflated avail/free, and run the ring off its own data
        # (found as BAD MAGIC / zero-header desyncs under multi-worker
        # load).  A native-format ('Q') cast memoryview stores via one
        # 8-byte memcpy — a single aligned mov on x86-64, which the shm
        # van already requires (little-endian, TSO).
        self._ctr = self._view[:16].cast("Q")  # [0]=head, [1]=tail

    # -- counter accessors ------------------------------------------------
    def _head(self) -> int:
        return self._ctr[0]

    def _tail(self) -> int:
        return self._ctr[1]

    def _closed(self) -> bool:
        return self._mm[16] != 0

    def mark_closed(self) -> None:
        try:
            self._mm[16] = 1
        except ValueError:  # already unmapped
            pass

    # -- producer side ----------------------------------------------------
    def write(self, data, wait=None) -> None:
        """Block until all of ``data`` is in the ring (socket sendall
        semantics).  Raises ConnectionError if the peer closed.
        ``wait(timeout) -> bool`` replaces the stall sleep when given;
        returning False means the peer died without setting the closed
        flag (e.g. SIGKILL) — the van passes a select() on its control
        socket so death wakes the wait instantly."""
        src = memoryview(data)
        if src.nbytes and src.format != "B":
            src = src.cast("B")
        off = 0
        n = src.nbytes
        sleep = 2e-5
        stalls = 0
        while off < n:
            try:
                head, tail = self._head(), self._tail()
            except ValueError:  # our own side already closed/unmapped
                raise ConnectionError("shm ring closed") from None
            free = self.capacity - (head - tail)
            if free == 0:
                if self._closed():
                    raise ConnectionError("shm ring peer closed")
                if wait is not None:
                    if not wait(sleep):
                        raise ConnectionError("shm ring peer closed")
                else:
                    time.sleep(sleep)
                stalls += 1
                sleep = min(sleep * 2, _stall_cap(stalls))
                continue
            sleep = 2e-5
            stalls = 0
            pos = head % self.capacity
            chunk = min(free, n - off, self.capacity - pos)
            try:
                self._view[_HDR + pos : _HDR + pos + chunk] = src[off : off + chunk]
                # publish AFTER the payload bytes are in place
                self._ctr[0] = head + chunk
            except ValueError:
                raise ConnectionError("shm ring closed") from None
            off += chunk
        if self._closed():
            raise ConnectionError("shm ring peer closed")

    # -- consumer side ----------------------------------------------------
    def recv_into(self, buf, nbytes: int = 0, wait=None) -> int:
        """Socket recv_into semantics: block until ≥1 byte, copy up to
        ``nbytes`` (or len(buf)), return count; 0 once closed+drained.
        ``wait`` as in :meth:`write`."""
        dst = memoryview(buf)
        if dst.nbytes and dst.format != "B":
            dst = dst.cast("B")
        want = nbytes or dst.nbytes
        sleep = 2e-5
        stalls = 0
        dead = False
        while True:
            try:
                head, tail = self._head(), self._tail()
            except ValueError:  # our own side already closed/unmapped
                return 0
            avail = head - tail
            if avail:
                pos = tail % self.capacity
                chunk = min(avail, want, self.capacity - pos)
                try:
                    dst[:chunk] = self._view[_HDR + pos : _HDR + pos + chunk]
                    self._ctr[1] = tail + chunk
                except ValueError:
                    return 0
                return chunk
            if dead:
                return 0
            if self._closed() or (wait is not None and not wait(sleep)):
                # peer closed/died — but bytes may have landed between
                # the avail check above and noticing the death; loop one
                # more time so a final response written just before the
                # peer exited is still delivered
                dead = True
                continue
            if wait is None:
                time.sleep(sleep)
            stalls += 1
            sleep = min(sleep * 2, _stall_cap(stalls))

    def close(self) -> None:
        self.mark_closed()
        try:
            self._ctr.release()
            self._view.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass
        if self._unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass
