"""Framed TCP transport for the PS plane — the ZeroMQ-van replacement.

The reference's inter-host layer is ps-lite's "van" over ZMQ TCP / RDMA /
UCX (SURVEY §2.4).  The TPU build's DCN transport starts as plain TCP with
a fixed 32-byte binary header + raw payload (zero-copy into numpy on
receive); the framing is transport-agnostic so an RDMA-class backend can
slot in behind the same interface.

Header layout (network byte order):

    u8  magic      0xB5
    u8  op         Op enum
    u8  status     0 = OK
    u8  flags
    u32 seq        request/response matching id
    u64 key        partition key
    u32 cmd        Cantor-encoded (RequestType, DataType) (common.cc:98)
    u32 version    round / generation
    u64 length     payload byte count

Optional trace context (docs/observability.md): when ``status`` carries
``TRACE_FLAG`` (bit 7 — requests are otherwise status 0, so the bit is
free on the request direction), a 16-byte block ``u64 trace_id + u64
span_id`` follows the header, BEFORE the payload; ``length`` still
counts only the payload.  Decoders that don't trace (the native C++
engine) skip the block — old and new frames interoperate both ways.

Optional end-to-end integrity (docs/robustness.md "Wire integrity"):
when ``status`` carries ``CHECKSUM_FLAG`` (bit 6), a 4-byte big-endian
CRC32C follows the header (after the trace block when both are
present), BEFORE the payload.  The CRC covers EVERYTHING after the
fixed 32-byte header except itself — the trace block and the whole
payload (fused member blocks, span trailer, compressed bytes included)
— so a single flipped payload bit that TCP's 16-bit checksum missed is
detected at the receiver before the frame reaches any sum core or
demux.  Stamping is opt-in per process (``BYTEPS_WIRE_CHECKSUM=1``,
data-plane ops only — control frames stay byte-identical);
verification is self-describing: any receiver that sees the flag
checks it.  A mismatch is a DROP (:class:`ChecksumError` after the
stream is fully consumed — framing survives), healed by the ordinary
deadline/retry + exactly-once-ledger machinery; repeated mismatches on
one connection escalate to teardown (``BYTEPS_CHECKSUM_CONN_LIMIT``)
so connection revival re-dials a possibly-bad path.
"""

from __future__ import annotations

import enum
import os
import socket
import struct
import threading
from typing import Optional, Tuple

MAGIC = 0xB5
HEADER_FMT = "!BBBBIQIIQ"
HEADER_SIZE = struct.calcsize(HEADER_FMT)
assert HEADER_SIZE == 32

#: status-byte bit: a 16-byte (trace_id, span_id) block follows the header
TRACE_FLAG = 0x80
_TRACE_FMT = "!QQ"
TRACE_SIZE = struct.calcsize(_TRACE_FMT)
assert TRACE_SIZE == 16

#: status-byte bit: a 4-byte big-endian CRC32C of (trace block + payload)
#: follows the header (after the trace block), BEFORE the payload
CHECKSUM_FLAG = 0x40
_CHECKSUM_FMT = "!I"
CHECKSUM_SIZE = struct.calcsize(_CHECKSUM_FMT)
assert CHECKSUM_SIZE == 4

#: status-byte bit: the payload is a lossless container
#: (compression/lossless.py frame format) — the header ``length`` and
#: the CRC32C cover the COMPRESSED bytes, so integrity is verified
#: before the decompressor runs.  Versioning by construction: no
#: pre-lossless decoder ever sets or strips this bit, so an old
#: receiver sees a nonzero status and refuses the frame cleanly
#: instead of mis-parsing the body (wire.h kLosslessFlag).
LOSSLESS_FLAG = 0x20


class ChecksumError(ValueError):
    """A frame's CRC32C did not match its bytes — payload corruption the
    framing layer cannot see.  Raised AFTER the frame is fully consumed,
    so the stream stays framed and the caller may keep the connection
    (drop semantics: discard the frame, let deadlines/retries heal it).
    A ``ValueError`` subclass so callers that treat malformed bodies as
    retryable failures (migration shipping, control decode guards)
    already do the right thing."""

    def __init__(self, op, expected: int, got: int) -> None:
        super().__init__(
            f"wire checksum mismatch on {getattr(op, 'name', op)} frame: "
            f"expected {expected:#010x}, computed {got:#010x}"
        )
        self.op = op
        self.expected = expected
        self.got = got


# the lossless twin of ChecksumError, re-exported so receivers catch the
# two corrupt-frame classes side by side (server _serve_conn_loop,
# client _recv_loop, tools/wire_fuzz.py)
from byteps_tpu.compression.lossless import LosslessError  # noqa: E402


class Op(enum.IntEnum):
    # scheduler plane (ps-lite Postoffice equivalents)
    REGISTER = 1      # node → scheduler: {role, host, port}
    ADDRBOOK = 2      # scheduler → nodes: {rank, servers: [(host, port)]}
    BARRIER = 3       # node → scheduler; response released when group full
    # data plane (KVWorker/KVServer equivalents)
    INIT = 10         # declare key storage; response is the init barrier
    PUSH = 11         # gradient payload; response = ack
    PULL = 12         # request payload; response = aggregated bytes
    REGISTER_COMPRESSOR = 13  # serialized compressor kwargs (operations.cc:396-408)
    FUSED = 14        # multi-key fused push+pull: request packs N small
                      # sub-pushes for one server; the response is the N
                      # merged round payloads (small-tensor coalescing,
                      # docs/perf.md).  One seq / deadline / retry state
                      # covers the whole frame.
    # control
    PING = 20
    SHUTDOWN = 21
    QUERY = 22        # cluster liveness snapshot (heartbeat ages)
    # recovery plane (docs/robustness.md "healing flow"): a worker that
    # exhausted its RPC retries against a LIVE server asks that server
    # for its authoritative per-key round/ledger state, replays only the
    # journaled pushes the server never absorbed, and rejoins in place —
    # no global re-init barrier, no peer participation.  Served by BOTH
    # engines (the C++ server answers from its native ledger).
    RESYNC_QUERY = 23  # worker → server: {worker flag, keys of interest}
    RESYNC_STATE = 24  # server → worker: per-key {store_version, seen, ...}
    # elastic resharding plane (docs/robustness.md "migration flow"): the
    # key→server ownership map is versioned (consistent-hash ring,
    # epoch-stamped like worker membership); when the server set changes
    # the old owner ships each re-homed key's authoritative state —
    # store + exactly-once ledger + init-token record — to the new owner,
    # and answers stale-map requests with a redirect carrying the new map
    # epoch.  Workers chase the redirect the way they chase RESYNC;
    # the migrated ledger makes the handoff exactly-once.
    MIGRATE_STATE = 25  # old owner → new owner: one key's full state
    WRONG_OWNER = 26    # server → worker reply: {new owner rank};
                        # header ``version`` carries the new map epoch


# --- end-to-end wire integrity (CHECKSUM_FLAG) ----------------------------
#
# CRC32C (Castagnoli, the iSCSI/ext4 polynomial — hardware-accelerated on
# every server CPU this decade, and the one UCCL-Zip-style lossless wire
# transforms standardize on) over everything after the fixed header.
# The Python side prefers the shared C implementation in native/wire.h
# (``bps_wire_crc32c`` via ctypes — the SAME code the C++ engines stamp
# and verify with, so the two sides cannot drift) and falls back to a
# table-driven pure-Python loop when the lib isn't built.

#: ops that carry a checksum when BYTEPS_WIRE_CHECKSUM=1 — the data
#: plane only; control frames (scheduler link, PING/SHUTDOWN/QUERY)
#: stay byte-identical so arming the knob never perturbs the control
#: wire (mirrored by wire.h checksum_op — change both together)
_CHECKSUM_OPS = frozenset({10, 11, 12, 13, 14, 23, 24, 25, 26})

_TRUTHY_OFF = ("", "0", "false", "no", "off")


def wire_checksum_enabled() -> bool:
    """Stamp outgoing data-plane frames with CRC32C?  Read from
    ``BYTEPS_WIRE_CHECKSUM`` on every call (a dict lookup — cheap against
    a frame encode) so tests toggling the env need no cache reset.
    Verification is NOT gated on this: any received frame carrying
    ``CHECKSUM_FLAG`` is checked."""
    return os.environ.get("BYTEPS_WIRE_CHECKSUM", "").lower() not in _TRUTHY_OFF


#: ops whose payloads auto-compress with the lossless frame codec when
#: BYTEPS_WIRE_LOSSLESS=1 — the bit-exactness-critical control plane
#: only (RESYNC_STATE snapshots, MIGRATE_STATE store+ledger+opt-slot
#: shipments): exactly the megabyte-class frames lossy codecs can't
#: touch.  Gradient-plane frames keep their own per-key codecs.
#: Mirrored by wire.h lossless_op — change both together.
_LOSSLESS_OPS = frozenset({24, 25})


def wire_lossless_enabled() -> bool:
    """Compress outgoing control-plane frames with the lossless codec
    (``BYTEPS_WIRE_LOSSLESS``, default off)?  Same per-call env read as
    :func:`wire_checksum_enabled`.  Decode is NOT gated on this: any
    received frame carrying ``LOSSLESS_FLAG`` is decompressed."""
    return os.environ.get("BYTEPS_WIRE_LOSSLESS", "").lower() not in _TRUTHY_OFF


def checksum_conn_limit() -> int:
    """Mismatches tolerated on one connection before the receiver tears
    it down (``BYTEPS_CHECKSUM_CONN_LIMIT``, default 8; 0 = never) —
    the escalation from "one flipped bit, drop and retry" to "this path
    is corrupting repeatedly, revive the connection"."""
    v = os.environ.get("BYTEPS_CHECKSUM_CONN_LIMIT", "")
    try:
        n = int(v) if v else 8
    except ValueError:
        return 8
    # negatives/garbage = default, matching wire.h checksum_env_conn_limit
    # (a negative here would mean "drop on the FIRST mismatch" — the
    # opposite of what -1 conventionally asks for)
    return n if n >= 0 else 8


_CRC32C_POLY = 0x82F63B78
_crc_table: Optional[list] = None
#: ctypes fast path through native/wire.h crc32c (None = unresolved,
#: False = lib unavailable — pure-Python table takes over)
_crc_native = None


def _crc32c_table() -> list:
    global _crc_table
    if _crc_table is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ (_CRC32C_POLY if c & 1 else 0)
            tbl.append(c)
        _crc_table = tbl
    return _crc_table


def _resolve_crc_native():
    global _crc_native
    try:
        from byteps_tpu.native import get_lib

        lib = get_lib()
        if lib is not None and hasattr(lib, "bps_wire_crc32c"):
            _crc_native = lib.bps_wire_crc32c
        else:
            _crc_native = False
    except Exception:  # noqa: BLE001 — any import/build issue → fallback
        _crc_native = False
    return _crc_native


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of ``data`` (bytes / bytearray / memoryview / ndarray),
    chained: ``crc32c(b, crc32c(a)) == crc32c(a + b)``.  Uses the shared
    native implementation when the lib is built (the data plane's
    actual cost), pure Python otherwise."""
    native = _crc_native if _crc_native is not None else _resolve_crc_native()
    n = len(data)
    if not n:
        return crc
    if native:
        import numpy as _np

        a = _np.frombuffer(data, dtype=_np.uint8)  # no-copy view
        return int(native(a.ctypes.data, n, crc))
    tbl = _crc32c_table()
    c = crc ^ 0xFFFFFFFF
    for b in bytes(data):
        c = (c >> 8) ^ tbl[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def frame_checksum(trace: Optional[Tuple[int, int]], payload) -> int:
    """The CRC32C a frame's checksum block must carry: everything after
    the fixed header except the block itself — the 16-byte trace block
    (when present) chained with the payload bytes."""
    crc = 0
    if trace is not None:
        crc = crc32c(struct.pack(_TRACE_FMT, trace[0], trace[1]))
    return crc32c(payload, crc)


class Message:
    __slots__ = (
        "op", "status", "flags", "seq", "key", "cmd", "version", "payload",
        "trace", "checksum", "lossless", "_lossless_applied",
    )

    def __init__(
        self,
        op: Op,
        key: int = 0,
        payload: bytes = b"",
        seq: int = 0,
        cmd: int = 0,
        version: int = 0,
        status: int = 0,
        flags: int = 0,
        trace: Optional[Tuple[int, int]] = None,
        checksum: Optional[bool] = None,
        lossless: Optional[bool] = None,
    ) -> None:
        self.op = op
        self.status = status
        self.flags = flags
        self.seq = seq
        self.key = key
        self.cmd = cmd
        self.version = version
        self.payload = payload
        #: optional (trace_id, span_id) propagated in the trace-context
        #: header field (docs/observability.md); None = untraced frame
        self.trace = trace
        #: stamp a CHECKSUM_FLAG CRC32C block?  None (default) = follow
        #: BYTEPS_WIRE_CHECKSUM for data-plane ops; True/False force it
        #: (golden fixtures / fuzzing)
        self.checksum = checksum
        #: compress the payload with the lossless frame codec?  None
        #: (default) = follow BYTEPS_WIRE_LOSSLESS for _LOSSLESS_OPS;
        #: True = attempt on any op (the tuner's per-key lossless arm);
        #: False = never.  The frame carries LOSSLESS_FLAG only when the
        #: container actually came out smaller.
        self.lossless = lossless
        #: tri-state transform latch: None = not finalized, True/False =
        #: payload was / wasn't swapped for its compressed container —
        #: the transform runs exactly once even across send retries
        self._lossless_applied = None

    def _stamp_checksum(self) -> bool:
        ck = self.checksum
        if ck is None:
            return int(self.op) in _CHECKSUM_OPS and wire_checksum_enabled()
        return bool(ck)

    def _stamp_lossless(self) -> bool:
        """Finalize the lossless transform (idempotent): when the policy
        says compress AND the container wins, swap ``payload`` for the
        container and return True.  Must run before the header is packed
        — ``length`` and the CRC32C cover the bytes that actually ship,
        so integrity is verified before any receiver decompresses."""
        done = self._lossless_applied
        if done is not None:
            return done
        lz = self.lossless
        if lz is None:
            lz = int(self.op) in _LOSSLESS_OPS and wire_lossless_enabled()
        applied = False
        if lz:
            from byteps_tpu.compression.lossless import (
                MIN_BYTES, compress_frame,
            )

            if len(self.payload) >= MIN_BYTES:
                comp = compress_frame(self.payload)
                if len(comp) < len(self.payload):
                    self.payload = comp
                    applied = True
        self._lossless_applied = applied
        return applied

    def encode_header(self) -> bytes:
        lz = self._stamp_lossless()  # may swap payload — before pack/CRC
        ck = self._stamp_checksum()
        hdr = struct.pack(
            HEADER_FMT,
            MAGIC,
            int(self.op),
            self.status
            | (TRACE_FLAG if self.trace is not None else 0)
            | (CHECKSUM_FLAG if ck else 0)
            | (LOSSLESS_FLAG if lz else 0),
            self.flags,
            self.seq,
            self.key,
            self.cmd,
            self.version,
            len(self.payload),
        )
        if self.trace is not None:
            hdr += struct.pack(_TRACE_FMT, self.trace[0], self.trace[1])
        if ck:
            # computed once per frame per side; the scatter-gather send
            # below ships [header+trace+crc, payload] unchanged
            hdr += struct.pack(
                _CHECKSUM_FMT, frame_checksum(self.trace, self.payload)
            )
        return hdr

    def encode(self) -> bytes:
        return self.encode_header() + self.payload


def recv_into(sock: socket.socket, view: memoryview) -> None:
    """Receive exactly len(view) bytes INTO the caller's buffer — the
    zero-copy receive primitive (ps-lite ZPull pulls into the caller's
    SArray, core_loops.cc:584-618)."""
    n = len(view)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    recv_into(sock, memoryview(buf))
    return bytes(buf)


def recv_header_ex(sock: socket.socket) -> tuple:
    """Read + parse one header, trace-, checksum- and lossless-aware;
    returns (op, status, flags, seq, key, cmd, version, length, trace,
    crc, lossless) where ``trace`` is (trace_id, span_id) or None,
    ``crc`` is the frame's CHECKSUM_FLAG CRC32C or None, and
    ``lossless`` says the payload is a compressed container.  All flag
    bits are consumed here — ``status`` comes back clean, so frames
    from stamping and non-stamping peers are indistinguishable
    downstream.  The caller that receives the payload owns verification
    and decompression (:func:`verify_checksum` / :func:`recv_message`)."""
    hdr = _recv_exact(sock, HEADER_SIZE)
    magic, op, status, flags, seq, key, cmd, version, length = struct.unpack(
        HEADER_FMT, hdr
    )
    if magic != MAGIC:
        raise ConnectionError(f"bad magic {magic:#x}")
    trace = None
    if status & TRACE_FLAG:
        trace = struct.unpack(_TRACE_FMT, _recv_exact(sock, TRACE_SIZE))
        status &= ~TRACE_FLAG
    crc = None
    if status & CHECKSUM_FLAG:
        (crc,) = struct.unpack(_CHECKSUM_FMT, _recv_exact(sock, CHECKSUM_SIZE))
        status &= ~CHECKSUM_FLAG
    lossless = bool(status & LOSSLESS_FLAG)
    if lossless:
        status &= ~LOSSLESS_FLAG
    return (Op(op), status, flags, seq, key, cmd, version, length, trace,
            crc, lossless)


def recv_header(sock: socket.socket) -> tuple:
    """Read + parse one header; returns
    (op, status, flags, seq, key, cmd, version, length).  Any trace
    context or checksum block on the frame is read off the stream and
    dropped (the optional-on-decode guarantee: a non-verifying consumer
    stays framed)."""
    return recv_header_ex(sock)[:8]


def verify_checksum(crc: Optional[int], trace: Optional[Tuple[int, int]],
                    payload, op=None) -> None:
    """Check a received frame's CRC32C against its bytes; no-op for
    unstamped frames (``crc`` None).  Raises :class:`ChecksumError` on
    mismatch — the frame is already fully consumed, so the caller may
    drop it and keep reading the stream."""
    if crc is None:
        return
    got = frame_checksum(trace, payload)
    if got != crc:
        raise ChecksumError(op, crc, got)


def recv_message(sock: socket.socket) -> Message:
    """Receive one frame; verifies the CHECKSUM_FLAG CRC32C when the
    sender stamped one, then decompresses a LOSSLESS_FLAG container —
    in that order, so the CRC is checked over the exact bytes that
    shipped and a corrupt container never reaches the decompressor
    unflagged.  Both failures (:class:`ChecksumError` /
    :class:`LosslessError`) raise AFTER the frame is consumed — drop
    semantics, the stream stays framed."""
    op, status, flags, seq, key, cmd, version, length, trace, crc, lossless = (
        recv_header_ex(sock)
    )
    payload = _recv_exact(sock, length) if length else b""
    verify_checksum(crc, trace, payload, op=op)
    if lossless:
        from byteps_tpu.compression.lossless import decompress_frame

        payload = decompress_frame(payload, op=op)
    return Message(
        op, key=key, payload=payload, seq=seq, cmd=cmd, version=version,
        status=status, flags=flags, trace=trace,
    )


def _send(sock: socket.socket, msg: Message) -> None:
    # header first: encode_header may finalize the lossless transform,
    # swapping msg.payload for its compressed container
    hdr = msg.encode_header()
    payload = msg.payload
    if not payload:
        sock.sendall(hdr)
        return
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:
        # van object without scatter-gather: header-then-payload, still no
        # concat copy of the payload
        sock.sendall(hdr)
        sock.sendall(payload)
        return
    # scatter-gather send: header + payload leave in ONE syscall with ZERO
    # payload memcpys (the kernel gathers straight from the caller's
    # buffer) — ps-lite's zero-copy ZPush property (core_loops.cc:538-582)
    bufs = [memoryview(hdr), memoryview(payload)]
    while bufs:
        sent = sendmsg(bufs)
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if bufs and sent:
            bufs[0] = bufs[0][sent:]


def send_message(sock: socket.socket, msg: Message, lock: Optional[threading.Lock] = None) -> None:
    if lock is not None:
        with lock:
            _send(sock, msg)
    else:
        _send(sock, msg)


def connect(host: str, port: int, timeout: float = 30.0) -> socket.socket:
    """Dial an address from the scheduler book; the van scheme is encoded
    in the host string (``unix://...`` → UDS, else TCP)."""
    from byteps_tpu.comm.van import van_for_address

    return van_for_address(host).connect(host, port, timeout=timeout)


def connect_control(host: str, port: int, timeout: float = 30.0) -> socket.socket:
    """Dial the scheduler (control plane).  When the process runs a
    chaos van AND ``BYTEPS_CHAOS_SCHED=1``, the connection is wrapped in
    the client-side fault layer so scheduler-link faults are
    deterministically injectable — ``BYTEPS_CHAOS_TARGET_PORT`` set to
    the scheduler port and symbolic ``BYTEPS_CHAOS_OPS`` names
    (REGISTER/PING/ADDRBOOK) compose (docs/robustness.md
    "Control-plane recovery").  Otherwise identical to :func:`connect`."""
    sock = connect(host, port, timeout=timeout)
    import os

    if os.environ.get("BYTEPS_VAN", "").startswith("chaos:"):
        from byteps_tpu.comm.chaos import wrap_control

        sock = wrap_control(sock, port)
    return sock


# --- multi-key fusion frames (Op.FUSED) ----------------------------------
#
# Request body (network byte order):
#     u32 count
#     count × [u64 key, u32 cmd, u32 version, u64 length, length bytes]
# Response body:
#     u32 count
#     count × [u64 key, u32 version, u64 length, length bytes]
#
# The outer 32-byte header carries the ROUTE key (first member), the frame
# seq, and the worker-identity flags byte; each member keeps its own key,
# Cantor-encoded cmd, and round version so the server sums every sub-push
# through the per-(worker, key) exactly-once ledger — a retried frame
# dedupes atomically per member key.
#
# Tracing (docs/observability.md): the PACK's span rides the outer
# header's trace-context field; the MEMBER span ids ride an optional
# trailer of count × u64 after the last member.  decode_fused_push reads
# exactly ``count`` members and ignores the trailer, so pre-observability
# decoders stay compatible; decode_fused_spans recovers the ids.

_FUSED_MEMBER_FMT = "!QIIQ"
_FUSED_MEMBER_SIZE = struct.calcsize(_FUSED_MEMBER_FMT)
_FUSED_REPLY_FMT = "!QIQ"
_FUSED_REPLY_SIZE = struct.calcsize(_FUSED_REPLY_FMT)


def encode_fused_push(members, span_ids=None) -> bytes:
    """Pack ``[(key, cmd, version, payload), ...]`` into one frame body.
    ``span_ids`` (one u64 per member, same order) appends the optional
    member-span trailer for distributed tracing."""
    parts = [struct.pack("!I", len(members))]
    for key, cmd, version, payload in members:
        parts.append(struct.pack(_FUSED_MEMBER_FMT, key, cmd, version, len(payload)))
        parts.append(bytes(payload) if not isinstance(payload, bytes) else payload)
    if span_ids:
        if len(span_ids) != len(members):
            raise ValueError("span_ids must match members 1:1")
        parts.append(struct.pack(f"!{len(span_ids)}Q", *span_ids))
    return b"".join(parts)


def _walk_fused_members(body: bytes) -> tuple:
    """→ (members, offset-after-last-member)."""
    (count,) = struct.unpack_from("!I", body, 0)
    off = 4
    members = []
    for _ in range(count):
        key, cmd, version, length = struct.unpack_from(_FUSED_MEMBER_FMT, body, off)
        off += _FUSED_MEMBER_SIZE
        if off + length > len(body):
            raise ValueError("fused frame truncated")
        members.append((key, cmd, version, body[off : off + length]))
        off += length
    return members, off


def decode_fused_push(body: bytes) -> list:
    """Inverse of :func:`encode_fused_push` → [(key, cmd, version, bytes)].
    A member-span trailer, if present, is ignored (old-decoder parity)."""
    return _walk_fused_members(body)[0]


def decode_fused_spans(body: bytes):
    """The member-span trailer of a fused frame → [span_id, ...], or
    None when the frame carries none (pre-observability sender)."""
    members, off = _walk_fused_members(body)
    if len(body) - off == 8 * len(members) and members:
        return list(struct.unpack_from(f"!{len(members)}Q", body, off))
    return None


def encode_fused_reply(members) -> bytes:
    """Pack ``[(key, version, payload), ...]`` into one reply body."""
    parts = [struct.pack("!I", len(members))]
    for key, version, payload in members:
        parts.append(struct.pack(_FUSED_REPLY_FMT, key, version, len(payload)))
        parts.append(payload)
    return b"".join(parts)


def decode_fused_reply(body: bytes) -> list:
    """Inverse of :func:`encode_fused_reply` → [(key, version, bytes)]."""
    (count,) = struct.unpack_from("!I", body, 0)
    off = 4
    members = []
    for _ in range(count):
        key, version, length = struct.unpack_from(_FUSED_REPLY_FMT, body, off)
        off += _FUSED_REPLY_SIZE
        if off + length > len(body):
            raise ValueError("fused reply truncated")
        members.append((key, version, body[off : off + length]))
        off += length
    return members


def decode_liveness(payload: bytes) -> dict:
    """Decode an Op.QUERY liveness reply: JSON stringifies rank keys;
    restore ints so consumers index by rank."""
    import json

    raw = json.loads(payload.decode())
    return {role: {int(r): age for r, age in d.items()} for role, d in raw.items()}


# --- recovery-plane frames (Op.RESYNC_QUERY / Op.RESYNC_STATE) ------------
#
# JSON bodies, like the control plane: resync is a rare, human-debuggable
# recovery RPC, not a data-plane hot path, and JSON keeps it greppable in
# packet dumps.  Served by BOTH engines (docs/robustness.md): the C++
# server answers from its own ledger with byte-compatible state bodies
# (ps_server.cc encode_resync_state_bytes, pinned by the golden wire
# fixtures); a PRE-parity native binary answers with a nonzero status
# and the worker's heal path falls back to the global re-init barrier.
#
# Query body:  {"worker": <flags byte>, "keys": [<u64 key>, ...]}
#              (empty "keys" = every key the server holds)
# State body:  {"keys": {"<key>": {"store_version": v, "seen": s,
#                                  "recv_count": c, "init": true}}}
#              where "seen" is the newest version of THIS worker's pushes
#              the server has absorbed into its exactly-once ledger.


def encode_resync_query(worker_flag: int, keys) -> bytes:
    """Body of an Op.RESYNC_QUERY frame."""
    import json

    return json.dumps(
        {"worker": int(worker_flag), "keys": [int(k) for k in keys]}
    ).encode()


def decode_resync_query(payload: bytes) -> Tuple[int, list]:
    """→ (worker_flag, [key, ...]); raises ValueError on a malformed body."""
    import json

    raw = json.loads(payload.decode())
    if not isinstance(raw, dict):
        raise ValueError("resync query body must be a JSON object")
    return int(raw.get("worker", 0)), [int(k) for k in raw.get("keys", [])]


def encode_resync_state(states: dict) -> bytes:
    """Body of an Op.RESYNC_STATE reply; ``states`` maps int key →
    {"store_version", "seen", "recv_count", "init"}."""
    import json

    return json.dumps({"keys": {str(k): v for k, v in states.items()}}).encode()


def decode_resync_state(payload: bytes) -> dict:
    """Inverse of :func:`encode_resync_state` → {int key: info dict}."""
    import json

    raw = json.loads(payload.decode())
    if not isinstance(raw, dict) or not isinstance(raw.get("keys", {}), dict):
        raise ValueError("resync state body must be a JSON object")
    return {int(k): v for k, v in raw.get("keys", {}).items()}


# --- resharding frames (Op.MIGRATE_STATE / Op.WRONG_OWNER) ----------------
#
# MIGRATE_STATE body: u32 json length + JSON metadata + raw store bytes +
# raw accumulator bytes.  The metadata (key, map epoch, dtype, round
# state, the per-(worker) exactly-once ledger ``push_seen``, the
# init-token record ``init_done``, compressor kwargs) is JSON like the
# RESYNC bodies — migration is a rare control-plane event and the state
# is already proven byte-stable in that encoding; the two big arrays ride
# raw after it so a multi-MB store pays no base64 tax.  The receiver acks
# with an empty MIGRATE_STATE reply (nonzero status = refused: resharding
# disabled, or an engine that cannot import state).
#
# WRONG_OWNER body: JSON {"owner": rank, "epoch": map_epoch}; the header
# ``version`` field carries the epoch too so a worker can chase without
# parsing the body.


def encode_migrate_state(meta: dict, store: bytes = b"",
                         accum: bytes = b"") -> bytes:
    """Body of an Op.MIGRATE_STATE frame; ``meta`` must already carry
    ``store_nbytes``/``accum_nbytes`` matching the raw tails."""
    import json

    head = json.dumps(meta).encode()
    return struct.pack("!I", len(head)) + head + store + accum


def decode_migrate_state(payload: bytes) -> Tuple[dict, bytes, bytes]:
    """Inverse of :func:`encode_migrate_state` → (meta, store, accum);
    raises ValueError on a malformed or truncated body."""
    import json

    if len(payload) < 4:
        raise ValueError("migrate frame too short")
    (hlen,) = struct.unpack_from("!I", payload, 0)
    if 4 + hlen > len(payload):
        raise ValueError("migrate frame truncated (header)")
    meta = json.loads(payload[4 : 4 + hlen].decode())
    if not isinstance(meta, dict):
        raise ValueError("migrate metadata must be a JSON object")
    off = 4 + hlen
    sn = int(meta.get("store_nbytes", 0))
    an = int(meta.get("accum_nbytes", 0))
    if sn < 0 or an < 0 or off + sn + an > len(payload):
        raise ValueError("migrate frame truncated (payload)")
    return meta, payload[off : off + sn], payload[off + sn : off + sn + an]


def decode_migrate_extra(payload: bytes, meta: dict) -> bytes:
    """The raw tail *behind* store+accum in a MIGRATE_STATE body —
    optimizer slot bytes (``meta["opt_slot_nbytes"]`` names the split).
    Kept out of :func:`decode_migrate_state`'s pinned 3-tuple so the
    PR 8 codec round-trip tests stay byte-for-byte valid; that decoder
    already tolerates trailing bytes, this one returns them."""
    (hlen,) = struct.unpack_from("!I", payload, 0)
    off = (
        4 + hlen
        + int(meta.get("store_nbytes", 0))
        + int(meta.get("accum_nbytes", 0))
    )
    return payload[off:]


# --- server-opt INIT profile block (bit 1 of the profile byte) ------------
#
# The PR 12 async profile appends ``!Bi`` (profile byte + staleness) to
# the 12-byte INIT body; sync keys stay byte-identical.  The server-side
# optimizer plane turns that byte into a bitmask (bit 0 = async, bit 1 =
# server-opt) and, when bit 1 is set, appends a rule block at offset 17:
# ``!H`` rule-name length + name bytes + ``!I`` hyperparam-JSON length +
# canonical JSON.  Engines that predate the bit reject the whole INIT
# with status=1 (the native engine counts ``native_server_opt_reject``),
# exactly like the async precedent — never a silent downgrade to SUM.


def encode_server_opt_block(rule: str, hp_json: str) -> bytes:
    """The rule block appended after the ``!Bi`` profile extension."""
    nb = str(rule).encode("utf-8")
    hb = hp_json.encode("utf-8")
    return struct.pack("!H", len(nb)) + nb + struct.pack("!I", len(hb)) + hb


def decode_server_opt_block(payload: bytes, off: int) -> Tuple[str, bytes]:
    """Inverse of :func:`encode_server_opt_block` → (rule name, raw
    hyperparam JSON bytes); raises ValueError when truncated."""
    if off + 2 > len(payload):
        raise ValueError("server-opt block truncated (name length)")
    (nlen,) = struct.unpack_from("!H", payload, off)
    off += 2
    if off + nlen + 4 > len(payload):
        raise ValueError("server-opt block truncated (name)")
    name = payload[off : off + nlen].decode("utf-8")
    off += nlen
    (hlen,) = struct.unpack_from("!I", payload, off)
    off += 4
    if off + hlen > len(payload):
        raise ValueError("server-opt block truncated (hyperparams)")
    return name, payload[off : off + hlen]


def encode_wrong_owner(epoch: int, owner: int) -> bytes:
    """Body of an Op.WRONG_OWNER reply."""
    import json

    return json.dumps({"owner": int(owner), "epoch": int(epoch)}).encode()


def decode_wrong_owner(payload: bytes) -> Tuple[int, int]:
    """→ (map_epoch, owner_rank); tolerant of an empty body (the header
    ``version`` field is the authoritative epoch) → (0, -1)."""
    import json

    try:
        raw = json.loads(payload.decode()) if payload else {}
    except (ValueError, UnicodeDecodeError):
        raw = {}
    if not isinstance(raw, dict):
        raw = {}
    return int(raw.get("epoch", 0)), int(raw.get("owner", -1))


def close_socket(sock: Optional[socket.socket]) -> None:
    """shutdown(SHUT_RDWR) then close.

    A bare ``close()`` while another thread is blocked in ``recv`` on the
    same socket does NOT close the fd (CPython defers it until the blocking
    call returns) — no FIN is sent and the peer never learns we left.
    ``shutdown`` sends the FIN immediately and wakes the blocked reader.
    """
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def listen(host: str = "0.0.0.0", port: int = 0) -> Tuple[socket.socket, int]:
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(128)
    return srv, srv.getsockname()[1]
