"""Pluggable transport "vans" for the PS data plane.

ps-lite ships three vans — ZeroMQ-TCP, RDMA verbs, UCX (SURVEY §2.4,
setup.py:312-330) — selected by env (``DMLC_ENABLE_RDMA``).  The TPU
build keeps the same seam: a Van owns listening/connecting for one
transport scheme while the 32-byte framing (transport.py) stays shared,
so an RDMA-class backend can slot in without touching the KV logic.

Vans:

- ``tcp``  — framed TCP (the ZMQ-class default).
- ``uds``  — Unix-domain stream sockets for same-host worker↔server
  traffic (honors ``BYTEPS_SOCKET_PATH`` like the reference's local
  plane, communicator.cc:99-107).
- ``shm``  — headers ride a UDS control socket, payload bytes move
  through mmap'd shared-memory rings (shm_ring.py): the bulk path makes
  no syscalls and touches no kernel socket buffers, the RDMA-class
  zero-copy seam (reference: ps-lite ZPush/ZPull zero-copy SArrays +
  BytePS_ShM staging, core_loops.cc:538-618, shared_memory.cc:28-50).
  Python server only (the native C++ engine speaks fd streams).

Selection: ``BYTEPS_VAN=tcp|uds|shm`` (server side — the address it
publishes in the scheduler book encodes the scheme, so clients need no
config).  Addresses stay ``(host, port)`` shaped for the control plane:
a UDS address is ``("unix://<path>", 0)``, an shm address is
``("shm+unix://<path>", 0)``.

``BYTEPS_VAN=chaos:<inner>`` wraps any van in the fault-injection layer
(comm/chaos.py): the published address gains a ``chaos+`` prefix so
dialing clients wrap their side too.  See docs/robustness.md.

``connect()`` retries refused/missing-endpoint dials with backoff for up
to ``BYTEPS_CONNECT_RETRY_S`` (default 2s, bounded by the connect
timeout): during cluster bring-up the worker/server/scheduler start
order no longer matters.  A down endpoint still fails fast enough for
the elastic rebuild path to notice.
"""

from __future__ import annotations

import os
import socket
import struct
import tempfile
import threading
import uuid
from typing import Tuple

UNIX_PREFIX = "unix://"
SHM_PREFIX = "shm+unix://"
CHAOS_PREFIX = "chaos+"

#: bring-up races surface as these: the peer's port/socket-file does not
#: exist yet (ECONNREFUSED / ENOENT) — transient by nature, so connect()
#: retries them with backoff inside a bounded budget
_RETRYABLE_DIAL_ERRORS = (ConnectionRefusedError, FileNotFoundError)


def _dial_retry_budget(timeout: float) -> float:
    """Seconds to keep re-dialing a refused endpoint.  Deliberately small
    by default: bring-up races close in well under 2s, while the elastic
    rebuild/revive paths need a DOWN server to fail fast."""
    raw = os.environ.get("BYTEPS_CONNECT_RETRY_S", "2")
    try:
        budget = float(raw or 0)
    except ValueError:
        budget = 2.0
    return max(0.0, min(budget, timeout))


def _dial_with_retry(dial, timeout: float):
    from byteps_tpu.comm.retry import call_with_retries

    return call_with_retries(
        dial, _dial_retry_budget(timeout), _RETRYABLE_DIAL_ERRORS
    )


class Van:
    """One transport scheme.  Framing/recv/send stay in transport.py."""

    name = "base"

    def listen(self, host: str) -> Tuple[socket.socket, str, int]:
        """Bind + listen; returns (socket, published_host, published_port)."""
        raise NotImplementedError

    def connect(self, host: str, port: int, timeout: float = 30.0) -> socket.socket:
        raise NotImplementedError


class TcpVan(Van):
    name = "tcp"

    def listen(self, host: str) -> Tuple[socket.socket, str, int]:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, 0))
        srv.listen(128)
        return srv, host, srv.getsockname()[1]

    def connect(self, host: str, port: int, timeout: float = 30.0) -> socket.socket:
        def dial():
            return socket.create_connection((host, port), timeout=timeout)

        sock = _dial_with_retry(dial, timeout)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock


class UdsVan(Van):
    name = "uds"

    def listen(self, host: str) -> Tuple[socket.socket, str, int]:
        base = os.environ.get("BYTEPS_SOCKET_PATH", tempfile.gettempdir())
        path = os.path.join(base, f"byteps_uds_{os.getpid()}_{uuid.uuid4().hex[:8]}.sock")
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(128)
        return srv, UNIX_PREFIX + path, 0

    def connect(self, host: str, port: int, timeout: float = 30.0) -> socket.socket:
        path = host[len(UNIX_PREFIX):]

        def dial():
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(timeout)
            try:
                sock.connect(path)
            except BaseException:
                sock.close()
                raise
            return sock

        sock = _dial_with_retry(dial, timeout)
        sock.settimeout(None)
        return sock


class ShmConnection:
    """Socket-shaped duplex connection whose payload path is a pair of
    shared-memory rings.  The UDS socket carries only the handshake and
    afterwards serves as the liveness backstop: a SIGKILLed peer never
    sets the ring's closed flag, but the kernel closes its fds, so an
    EOF on the control socket unblocks ring waits."""

    family = socket.AF_UNIX  # accept loops branch on family for TCP opts

    def __init__(self, sock: socket.socket, tx, rx, server_side: bool = False) -> None:
        self._sock = sock
        self._tx = tx
        self._rx = rx
        self._hs_lock = threading.Lock()
        if server_side:
            # handshake completes lazily on first use, in the server's
            # per-connection thread — doing it inside accept() would let
            # one stalled client head-of-line-block every other worker
            assert tx is None and rx is None
        else:
            sock.setblocking(False)
            tx.kick = rx.kick = self._kick

    def _ensure_handshake(self) -> None:
        if self._rx is not None:
            return
        with self._hs_lock:
            if self._rx is not None:
                return
            from byteps_tpu.comm.shm_ring import ShmRing
            from byteps_tpu.comm.transport import _recv_exact

            try:
                self._sock.settimeout(10.0)
                names = []
                for _ in range(2):
                    (ln,) = struct.unpack("!H", _recv_exact(self._sock, 2))
                    names.append(_recv_exact(self._sock, ln).decode())
                self._sock.settimeout(None)
                # client's c2s ring is our rx; attach then unlink
                # immediately — the mappings stay alive and the files
                # cannot leak whatever happens to either process
                rx = ShmRing(names[0], "consumer")
                tx = ShmRing(names[1], "producer")
            except Exception as e:
                raise ConnectionError(f"shm handshake failed: {e!r}") from e
            for name in names:
                try:
                    os.unlink(name)
                except OSError:
                    pass
            self._sock.setblocking(False)
            tx.kick = rx.kick = self._kick
            self._tx, self._rx = tx, rx

    def _kick(self) -> None:
        """Doorbell: one byte on the control socket wakes the peer's
        parked select() instantly (shm_ring.py park protocol).  A full
        socket buffer or dead peer is fine — the first means wakeups are
        already pending, the second is detected by the waiter."""
        try:
            self._sock.send(b"\x01")
        except (BlockingIOError, InterruptedError, OSError):
            pass

    def _peer_gone(self) -> bool:
        """Drain every pending doorbell byte; True on EOF (peer exited)."""
        try:
            while True:
                b = self._sock.recv(4096)
                if b == b"":
                    return True  # EOF: peer process exited
                if len(b) < 4096:
                    return False
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            return True

    def _wait(self, timeout: float) -> bool:
        """Ring park wait: sleep in select() on the control socket —
        woken instantly by the peer's doorbell byte or by a dead peer
        (kernel-closed fd → readable EOF).  Returns False when the peer
        is gone."""
        import select

        try:
            readable, _, _ = select.select([self._sock], [], [], timeout)
        except (OSError, ValueError):
            return False
        if readable:
            return not self._peer_gone()
        return True

    # socket surface used by transport.py ---------------------------------
    def sendall(self, data) -> None:
        self._ensure_handshake()
        self._tx.write(data, wait=self._wait)

    def recv_into(self, buf, nbytes: int = 0) -> int:
        self._ensure_handshake()
        return self._rx.recv_into(buf, nbytes, wait=self._wait)

    def recv(self, n: int) -> bytes:
        buf = bytearray(n)
        got = self.recv_into(buf, n)
        return bytes(buf[:got])

    def shutdown(self, how: int = socket.SHUT_RDWR) -> None:
        if self._tx is not None:
            self._tx.mark_closed()
        if self._rx is not None:
            self._rx.mark_closed()
        try:
            self._sock.shutdown(how)
        except OSError:
            pass

    def close(self) -> None:
        if self._tx is not None:
            self._tx.close()
        if self._rx is not None:
            self._rx.close()
        try:
            self._sock.close()
        except OSError:
            pass


class ShmListener:
    """Accept wrapper: completes the ring handshake before handing the
    connection to the server's per-connection thread."""

    def __init__(self, sock: socket.socket, path: str) -> None:
        self._sock = sock
        self._path = path

    def accept(self):
        # return immediately: the ring handshake completes lazily in the
        # per-connection thread (ShmConnection._ensure_handshake), so a
        # stalled or malicious client can neither head-of-line-block
        # other workers' connects nor kill the accept loop — its failure
        # surfaces as ConnectionError on first use, which server loops
        # already treat as a dropped connection
        conn, addr = self._sock.accept()
        return ShmConnection(conn, tx=None, rx=None, server_side=True), addr

    def shutdown(self, how: int = socket.SHUT_RDWR) -> None:
        try:
            self._sock.shutdown(how)
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            os.unlink(self._path)
        except OSError:
            pass


def _check_shm_arch() -> None:
    """The ring's data-then-counter publication order relies on x86-64's
    TSO memory model (shm_ring.py docstring); on weaker models (aarch64)
    a consumer could observe the head before the payload bytes.  Refuse
    loudly rather than corrupt gradients silently."""
    import platform

    if platform.machine() not in ("x86_64", "AMD64", "i686"):
        raise RuntimeError(
            "BYTEPS_VAN=shm requires an x86-64 host (TSO store ordering); "
            f"got {platform.machine()!r} — use the uds van instead"
        )


class ShmVan(Van):
    name = "shm"

    def listen(self, host: str) -> Tuple[object, str, int]:
        _check_shm_arch()
        base = os.environ.get("BYTEPS_SOCKET_PATH", tempfile.gettempdir())
        path = os.path.join(base, f"byteps_shm_{os.getpid()}_{uuid.uuid4().hex[:8]}.sock")
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(128)
        return ShmListener(srv, path), SHM_PREFIX + path, 0

    def connect(self, host: str, port: int, timeout: float = 30.0):
        from byteps_tpu.comm.shm_ring import ShmRing, create_ring_file

        _check_shm_arch()
        path = host[len(SHM_PREFIX):]

        def dial():
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(timeout)
            try:
                s.connect(path)
            except BaseException:
                s.close()
                raise
            return s

        sock = _dial_with_retry(dial, timeout)
        # default 512KB (was 16MB): payloads larger than the ring stream
        # through it with cheap park/kick handoffs, so capacity buys
        # nothing — while SMALL rings keep the working set in cache/TLB.
        # Measured (SCALING_r05.json r5_findings.ring_size): the 8w×8srv
        # cell cycled 64 conns × 2 × 16MB = 2GB of wrap-around pages and
        # ran at 274 MB/s aggregate; with 512KB rings the same cell runs
        # at 704 MB/s, and even a single pair moving 8MB payloads is ~8%
        # faster (2979 vs 2762 MB/s, van_bench).
        size = int(os.environ.get("BYTEPS_SHM_RING_BYTES", str(512 << 10)))
        created = []
        tx = rx = None
        try:
            c2s = create_ring_file(size, tag="c2s_")
            created.append(c2s)
            s2c = create_ring_file(size, tag="s2c_")
            created.append(s2c)
            # map BEFORE announcing the names: the server unlinks the
            # files the moment it has attached, so announcing first
            # races our own open() against that unlink.  unlink=True
            # covers a server that dies before attaching (ENOENT ok).
            tx = ShmRing(c2s, "producer", unlink=True)
            rx = ShmRing(s2c, "consumer", unlink=True)
            for name in (c2s, s2c):
                b = name.encode()
                sock.sendall(struct.pack("!H", len(b)) + b)
            sock.settimeout(None)
            return ShmConnection(sock, tx=tx, rx=rx)
        except Exception:
            # a half-built connection must not orphan its two rings in /dev/shm
            for ring in (tx, rx):
                if ring is not None:
                    ring.close()
            for path in created:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            try:
                sock.close()
            except OSError:
                pass
            raise


_VANS = {v.name: v for v in (TcpVan(), UdsVan(), ShmVan())}


def get_van(name: str = "") -> Van:
    """Server-side van selection (``BYTEPS_VAN``, default tcp).

    ``chaos:<inner>`` wraps the inner van in the fault-injection layer
    (comm/chaos.py) — its listener chaos-wraps accepted connections and
    publishes a ``chaos+``-prefixed address so clients wrap theirs."""
    name = name or os.environ.get("BYTEPS_VAN", "tcp")
    if name.startswith("chaos:"):
        inner = name[len("chaos:"):]
        if not inner or inner.startswith("chaos:"):
            # an empty inner name would re-read BYTEPS_VAN and recurse
            raise ValueError(
                f"BYTEPS_VAN={name!r}: chaos needs a concrete inner van "
                f"(chaos:tcp | chaos:uds | chaos:shm)"
            )
        from byteps_tpu.comm.chaos import make_chaos_van

        return make_chaos_van(get_van(inner))
    if name not in _VANS:
        raise ValueError(
            f"unknown van {name!r}; available: {sorted(_VANS)} "
            "(or chaos:<inner>)"
        )
    return _VANS[name]


def strip_chaos(host: str) -> str:
    """The inner-scheme address of a possibly chaos-prefixed one."""
    return host[len(CHAOS_PREFIX):] if host.startswith(CHAOS_PREFIX) else host


def van_for_address(host: str) -> Van:
    """Client-side dispatch: the scheme is encoded in the address."""
    if host.startswith(CHAOS_PREFIX):
        from byteps_tpu.comm.chaos import make_chaos_van

        return make_chaos_van(van_for_address(strip_chaos(host)))
    if host.startswith(SHM_PREFIX):
        return _VANS["shm"]
    return _VANS["uds"] if host.startswith(UNIX_PREFIX) else _VANS["tcp"]
