"""Pluggable transport "vans" for the PS data plane.

ps-lite ships three vans — ZeroMQ-TCP, RDMA verbs, UCX (SURVEY §2.4,
setup.py:312-330) — selected by env (``DMLC_ENABLE_RDMA``).  The TPU
build keeps the same seam: a Van owns listening/connecting for one
transport scheme while the 32-byte framing (transport.py) stays shared,
so an RDMA-class backend can slot in without touching the KV logic.

Vans:

- ``tcp``  — framed TCP (the ZMQ-class default).
- ``uds``  — Unix-domain stream sockets for same-host worker↔server
  traffic (the shm-class local path; honors ``BYTEPS_SOCKET_PATH`` like
  the reference's local plane, communicator.cc:99-107).

Selection: ``BYTEPS_VAN=tcp|uds`` (server side — the address it
publishes in the scheduler book encodes the scheme, so clients need no
config).  Addresses stay ``(host, port)`` shaped for the control plane:
a UDS address is ``("unix://<path>", 0)``.
"""

from __future__ import annotations

import os
import socket
import tempfile
import uuid
from typing import Tuple

UNIX_PREFIX = "unix://"


class Van:
    """One transport scheme.  Framing/recv/send stay in transport.py."""

    name = "base"

    def listen(self, host: str) -> Tuple[socket.socket, str, int]:
        """Bind + listen; returns (socket, published_host, published_port)."""
        raise NotImplementedError

    def connect(self, host: str, port: int, timeout: float = 30.0) -> socket.socket:
        raise NotImplementedError


class TcpVan(Van):
    name = "tcp"

    def listen(self, host: str) -> Tuple[socket.socket, str, int]:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, 0))
        srv.listen(128)
        return srv, host, srv.getsockname()[1]

    def connect(self, host: str, port: int, timeout: float = 30.0) -> socket.socket:
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock


class UdsVan(Van):
    name = "uds"

    def listen(self, host: str) -> Tuple[socket.socket, str, int]:
        base = os.environ.get("BYTEPS_SOCKET_PATH", tempfile.gettempdir())
        path = os.path.join(base, f"byteps_uds_{os.getpid()}_{uuid.uuid4().hex[:8]}.sock")
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(128)
        return srv, UNIX_PREFIX + path, 0

    def connect(self, host: str, port: int, timeout: float = 30.0) -> socket.socket:
        path = host[len(UNIX_PREFIX):]
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(path)
        sock.settimeout(None)
        return sock


_VANS = {v.name: v for v in (TcpVan(), UdsVan())}


def get_van(name: str = "") -> Van:
    """Server-side van selection (``BYTEPS_VAN``, default tcp)."""
    name = name or os.environ.get("BYTEPS_VAN", "tcp")
    if name not in _VANS:
        raise ValueError(f"unknown van {name!r}; available: {sorted(_VANS)}")
    return _VANS[name]


def van_for_address(host: str) -> Van:
    """Client-side dispatch: the scheme is encoded in the address."""
    return _VANS["uds"] if host.startswith(UNIX_PREFIX) else _VANS["tcp"]
