"""Core types, configuration, and tensor bookkeeping.

TPU-native equivalent of the reference's byteps/common/{common.h,global.cc}
layer: dtype table, pipeline stage enum, named-tensor registry with stable
key assignment, the partitioner, and the env-var config system.
"""
