"""Environment-variable configuration system.

The reference is configured purely through env vars (docs/env.md; SURVEY §5.6)
— no config files, no argparse in the core.  We keep the same knob names where
they still make sense on TPU, add TPU-specific ones under the same prefix,
and expose everything as one typed, reloadable ``Config`` object.

Reference consumption points cited per field.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def resolve_node_uid(explicit: Optional[str] = None) -> str:
    """Stable node identity for scheduler rejoin matching: explicit value
    (runtime state persists one across suspend/resume) > ``BYTEPS_NODE_UID``
    env (operator-assigned, survives process restart) > fresh uuid."""
    import uuid

    return explicit or os.environ.get("BYTEPS_NODE_UID") or uuid.uuid4().hex


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    return v.lower() not in ("0", "false", "no", "off")


def _env_str(name: str, default: str) -> str:
    v = os.environ.get(name)
    return v if v not in (None, "") else default


@dataclasses.dataclass
class Config:
    """Process-wide configuration snapshot.

    Call :func:`get_config` for the cached instance; :func:`reset_config`
    re-reads the environment (used by elastic ``resume()`` which rewrites
    DMLC_* env before re-init, common/__init__.py:75-82 in the reference).
    """

    # --- topology (DMLC_*, docs/env.md:1-37) ---
    role: str = "worker"  # worker | server | scheduler | joint
    num_worker: int = 1
    num_server: int = 0
    worker_id: int = 0
    ps_root_uri: str = "127.0.0.1"
    ps_root_port: int = 9000
    node_host: str = ""

    # --- local identity (communicator.cc:67-83) ---
    local_rank: int = 0
    local_size: int = 1
    global_rank: Optional[int] = None

    # --- pipeline tuning ---
    partition_bytes: int = 4096000  # BYTEPS_PARTITION_BYTES (global.cc:42,134)
    scheduling_credit: int = 0  # BYTEPS_SCHEDULING_CREDIT (scheduled_queue.cc:35); 0 = unlimited
    # queue discipline: "priority" = (priority desc, key asc) — the OSDI'20
    # scheduler; "fifo" = strict arrival order, the ablation baseline
    # (equivalent to the reference built without scheduling)
    scheduling: str = "priority"  # BYTEPS_SCHEDULING
    min_compress_bytes: int = 65536  # BYTEPS_MIN_COMPRESS_BYTES (global.cc:43,137)
    threadpool_size: int = 4  # BYTEPS_THREADPOOL_SIZE (global.cc:216)

    # --- adaptive compression (docs/gradient-compression.md "Compressed
    # wire path") ---
    # telemetry-driven codec selection: the COMPRESS stage tracks each
    # key's observed wire ratio (compressed bytes / raw bytes) and, after
    # the probe rounds, DISABLES the codec for keys where compression is
    # a loss (ratio above the cutoff — tiny tensors, k too close to n,
    # codec overhead beating the savings).  Disabling is worker-local and
    # per-key: the server's chain stays registered and serves raw pushes/
    # pulls for that key correctly (mixed-config rule), so no wire
    # coordination is needed.  Off by default — the configured codec is
    # a user decision until the operator opts into the policy.
    compression_auto: bool = False  # BYTEPS_COMPRESSION_AUTO
    # observed-ratio cutoff: a key whose mean wire ratio over the probe
    # rounds is >= this stops compressing (1.0 = only when compression
    # INFLATES the payload; the 0.9 default also drops near-break-even
    # codecs that pay CPU for <10% wire savings)
    compression_auto_ratio: float = 0.9  # BYTEPS_COMPRESSION_AUTO_RATIO
    # rounds observed per key before the policy verdict
    compression_auto_rounds: int = 3  # BYTEPS_COMPRESSION_AUTO_ROUNDS

    # --- small-tensor fusion (docs/perf.md) ---
    # partitions at or below this many BYTES take the FUSE stage: same-
    # server neighbors are packed into one multi-key Op.FUSED RPC instead
    # of per-key push+pull pairs — the hot path stops paying per-message
    # overhead for bias/layernorm-sized gradients.  0 disables fusion
    # (every partition keeps its own RPC).  BOTH server engines speak
    # Op.FUSED (the C++ data plane since the native-parity port); off by
    # default purely because coalescing only pays on many-small-key
    # workloads (docs/perf.md tuning note).
    fusion_threshold: int = 0  # BYTEPS_FUSION_THRESHOLD
    # fusion buffer capacity per destination server; a full buffer
    # flushes immediately
    fusion_bytes: int = 262144  # BYTEPS_FUSION_BYTES
    # max milliseconds a buffered partition may wait for more neighbors
    # before the pack is flushed anyway (latency backstop; the buffer
    # also flushes eagerly whenever the FUSE queue drains)
    fusion_cycle_ms: float = 2.0  # BYTEPS_FUSION_CYCLE_MS

    # --- key→server sharding (global.cc:158-180, 566-677) ---
    key_hash_fn: str = "djb2"  # naive | built_in | djb2 | sdbm | mixed
    enable_mixed_mode: bool = False
    mixed_mode_bound: int = 101  # global.cc:576-578 default
    built_in_hash_coef: int = 1

    # --- server (server.cc:412-456) ---
    server_engine_threads: int = 4  # BYTEPS_SERVER_ENGINE_THREAD
    server_enable_schedule: bool = False  # BYTEPS_SERVER_ENABLE_SCHEDULE
    enable_async: bool = False  # BYTEPS_ENABLE_ASYNC

    # --- multi-tenancy + asynchrony (docs/async.md) ---
    # job id this process belongs to (0 = the default single-tenant
    # namespace): every declared tensor's keys carry it in the top 16
    # bits of the wire key, so several jobs share one PS fleet without
    # key collisions (common/tenancy.py).  Nonzero jobs are a
    # Python-engine-only surface — the C++ server rejects their frames
    # cleanly (ROADMAP: native multi-tenant parity).
    job_id: int = 0  # BYTEPS_JOB_ID
    # weighted share of this job in the scheduler queues (client WFQ)
    # and the server's per-job service weighting — higher = more of the
    # fleet under contention.  Shares are proportional, never absolute:
    # a weight-1 job always progresses (starvation-free WFQ).
    job_priority: int = 1  # BYTEPS_JOB_PRIORITY
    # server-side admission quota for this job's request bytes, in
    # megaBYTES/s (same unit family as BYTEPS_VAN_RATE_MBYTES_S); 0 =
    # unlimited.  Excess requests are DELAYED (token bucket), never
    # dropped — job_quota_deferred counts the deferrals.
    job_quota_mbps: float = 0.0  # BYTEPS_JOB_QUOTA_MBPS
    # per-tenant gate credits in the client scheduler queues: this job's
    # in-flight byte budget (0 = only the global BYTEPS_SCHEDULING_CREDIT
    # applies).  The per-job dimension matters when one queue carries
    # several tenants (in-process fleets, tests).
    job_credit_bytes: int = 0  # BYTEPS_JOB_CREDIT_BYTES
    # async push_pull profile (docs/async.md): this worker's keys are
    # initialized async — the server applies pushes immediately to the
    # authoritative store and pulls return current state, no round
    # barrier.  Per-tensor overridable via declare kwargs
    # (byteps_async="0"/"1").
    async_mode: bool = False  # BYTEPS_ASYNC
    # bounded staleness for async keys (SSP): a pull at round v parks
    # until every peer worker's applied-push version is >= v - N.
    # -1 = unbounded (pure async); 0 degenerates to sequential
    # consistency (every pull waits for all of its round's pushes).
    staleness_bound: int = -1  # BYTEPS_STALENESS_BOUND
    # server-side optimizer plane (docs/architecture.md "Server-side
    # optimizer"): "" = off (servers SUM, workers own the optimizer);
    # a rule name ("sgd" / "momentum" / "adam") declares every float
    # tensor's INIT with the server-opt profile — workers push
    # gradients and pull UPDATED PARAMETERS.  Per-tensor overridable
    # via declare kwargs (byteps_server_opt="adam",
    # byteps_server_opt_hp={"lr": 0.001}).  Python-engine servers
    # only; the native engine rejects the profile cleanly.
    server_opt: str = ""  # BYTEPS_SERVER_OPT
    # JSON hyperparams for the fleet-wide BYTEPS_SERVER_OPT rule, e.g.
    # '{"lr": 0.01, "momentum": 0.9}' — per-tensor kwargs win.
    server_opt_hp: str = ""  # BYTEPS_SERVER_OPT_HP
    # per-job step-time SLO in seconds (0 = off): a completed step
    # slower than this fires the flight recorder's slo_breach trigger
    # (rate-limited bundle, flight_trigger{rule="slo_breach"}).
    job_slo_s: float = 0.0  # BYTEPS_JOB_SLO_S
    # --- failure detection (ps-lite heartbeats, SURVEY §5.3) ---
    heartbeat_interval: float = 5.0  # BYTEPS_HEARTBEAT_INTERVAL; 0 disables
    # scheduler-side liveness policy: a registered node whose heartbeat
    # age exceeds this is evicted from the membership (book re-broadcast,
    # rounds re-sized) — 0 disables eviction (ages stay observable via
    # Op.QUERY, the pre-policy behavior)
    dead_node_timeout_s: float = 0.0  # BYTEPS_DEAD_NODE_TIMEOUT_S

    # --- control-plane recovery (docs/robustness.md "Control-plane
    # recovery") ---
    # scheduler-link loss no longer latches the node dead: a reconnect
    # state machine redials DMLC_PS_ROOT_URI:PORT this many times
    # (after the first loss) while the data plane keeps training on the
    # last-adopted book.  0 restores the legacy terminal latch.
    sched_reconnect_retries: int = 20  # BYTEPS_SCHED_RECONNECT_RETRIES
    # exponential-backoff base between redials (full jitter, capped 10s)
    sched_reconnect_backoff_s: float = 0.5  # BYTEPS_SCHED_RECONNECT_BACKOFF_S
    # scheduler-side rejoin grace: a RESTARTED scheduler (one whose
    # registrants report a prior incarnation) waits this long for every
    # previously-reported rank to re-REGISTER before adopting the
    # partial population and emitting books — slow reconnectors are not
    # mass-evicted at rebirth.  Irrelevant on a fresh first boot.
    sched_rejoin_window_s: float = 15.0  # BYTEPS_SCHED_REJOIN_WINDOW_S

    # --- per-RPC deadlines + idempotent retry (self-healing data plane) ---
    # attempts AFTER the first before a push/pull/init surfaces its error
    rpc_retries: int = 2  # BYTEPS_RPC_RETRIES; 0 restores fail-fast
    # per-attempt deadline: a server that neither answers nor closes the
    # connection within this window is treated as failed (the connection
    # is torn down and the RPC retried).  0 disables the timer — only
    # connection death then triggers retry; hung servers are left to the
    # scheduler's eviction policy.
    rpc_deadline_s: float = 0.0  # BYTEPS_RPC_DEADLINE_S
    # exponential-backoff base between attempts (full jitter, capped 2s)
    rpc_backoff_s: float = 0.1  # BYTEPS_RPC_BACKOFF_S
    # separate deadline for the init-push barrier, whose ack legitimately
    # waits for every PEER worker: must exceed worst-case worker skew, so
    # it is NOT covered by rpc_deadline_s.  0 = none (default); chaos
    # tests set it small to heal dropped init acks.
    init_deadline_s: float = 0.0  # BYTEPS_INIT_DEADLINE_S
    # synchronous push_pull resubmits a DegradedError'd step this many
    # times (exactly-once safe; api.py) before surfacing the error
    degraded_step_retries: int = 0  # BYTEPS_DEGRADED_STEP_RETRIES

    # --- recovery plane (docs/robustness.md "healing flow") ---
    # rounds of emitted push payloads retained per key by the worker-side
    # round journal (comm/journal.py); a worker that exhausts its RPC
    # retries against a LIVE server replays exactly the journaled rounds
    # the server reports missing (Op.RESYNC_QUERY) and rejoins in place.
    # 0 disables journaling (resync then heals only lost-ack give-ups).
    journal_rounds: int = 2  # BYTEPS_JOURNAL_ROUNDS
    # total byte cap across all journaled payloads; oldest rounds evicted
    journal_bytes: int = 64 << 20  # BYTEPS_JOURNAL_BYTES
    # wall-clock budget for one heal attempt (server resync query +
    # journal replay); 0 disables the in-place heal entirely — give-ups
    # surface DegradedError immediately, the pre-recovery behavior
    resync_deadline_s: float = 5.0  # BYTEPS_RESYNC_DEADLINE_S

    # --- elastic server resharding (docs/robustness.md "migration flow") ---
    # live key migration on server join/leave: ownership is an
    # epoch-stamped consistent-hash ring, old owners ship each re-homed
    # key's state (store + exactly-once ledger + init tokens) to the new
    # owner over Op.MIGRATE_STATE, and stale-map workers chase
    # Op.WRONG_OWNER redirects — no cluster-wide re-init barrier.  Off
    # (default): a server resize re-homes keys via the hash fns and
    # forces the re-init barrier (the pre-resharding behavior).
    elastic_reshard: bool = False  # BYTEPS_ELASTIC_RESHARD
    # virtual nodes per server rank on the ownership ring (also fn="ring")
    ring_vnodes: int = 64  # BYTEPS_RING_VNODES
    # how long a new owner parks requests for a key whose migration is
    # inbound before dropping them back to the caller's retry path
    migrate_deadline_s: float = 10.0  # BYTEPS_MIGRATE_DEADLINE_S

    # --- transport (ps-lite van lanes) ---
    # parallel TCP connections per server, partitions striped across them
    # by key — the implementable analogue of the reference's RDMA/UCX
    # multi-lane vans (setup.py:312-330) for DCN-class cross-host links
    # where one stream cannot fill the pipe.  1 = single stream (default).
    tcp_streams: int = 1  # BYTEPS_TCP_STREAMS
    # C++ worker data plane (native/ps_client.cc): framing, demux, and
    # payload receive on GIL-free lane threads — the core_loops.cc:538-618
    # analogue.  Applies to tcp/uds server links when the native lib is
    # built; the shm van keeps the Python client (mmap bulk path).
    native_client: bool = False  # BYTEPS_NATIVE_CLIENT

    # --- flight recorder + anomaly triggers (docs/observability.md
    # "Flight recorder & doctor") ---
    # always-on bounded ring of per-step records stamped by the engine
    # at round completion (servers stamp per heartbeat beat); 0 disables
    # the recorder AND the trigger engine entirely
    flight_steps: int = 256  # BYTEPS_FLIGHT_STEPS
    # slow-step / straggler / hot-stripe sensitivity: a step (or one
    # peer's p99) must exceed the rolling/peer median by this factor
    flight_slow_factor: float = 3.0  # BYTEPS_FLIGHT_SLOW_FACTOR
    # queue-stall bound: a stage dwell p99 past this many seconds in one
    # step fires the queue_stall trigger
    flight_stall_s: float = 5.0  # BYTEPS_FLIGHT_STALL_S
    # where triggered diagnostic bundles land ("" = <trace_dir>/flight_bundles)
    flight_dir: str = ""  # BYTEPS_FLIGHT_DIR
    # per-rule bundle rate limit: one dump per rule per this many seconds
    # (triggers past the limit still count in flight_trigger{rule})
    flight_bundle_s: float = 60.0  # BYTEPS_FLIGHT_BUNDLE_S
    # upload dumped trigger bundles (compact form) over the control
    # plane into the SCHEDULER's BYTEPS_FLIGHT_DIR — fleet-central
    # incident evidence beside the autotuner's decision bundles
    flight_upload: bool = False  # BYTEPS_FLIGHT_UPLOAD

    # --- debug / trace / observability (global.cc:113-124; docs/observability.md) ---
    log_level: str = "WARNING"
    trace_on: bool = False
    trace_start_step: int = 10
    trace_end_step: int = 20
    trace_dir: str = "."
    # distributed spans (docs/observability.md): with tracing on, engine
    # tasks get trace/span ids that ride every framed RPC and the server
    # stamps child spans.  BYTEPS_TRACE_SPANS=0 keeps the classic
    # per-tensor stage envelopes but drops span events + wire context.
    trace_spans: bool = True  # BYTEPS_TRACE_SPANS
    telemetry_on: bool = False
    # Prometheus text exposition port, served per process (worker,
    # server, and the scheduler's cluster aggregate).  0 disables.  When
    # several processes share a host and the port is taken, the process
    # falls back to an ephemeral port and logs it.
    metrics_port: int = 0  # BYTEPS_METRICS_PORT
    force_distributed: bool = False  # BYTEPS_FORCE_DISTRIBUTED (global.cc:149-152)
    debug_sample_tensor: str = ""

    # --- TPU-native additions (no reference analogue) ---
    mesh_shape: str = ""  # e.g. "dp:8" or "dp:4,tp:2" — override auto mesh
    ici_reduce: str = "scatter_gather"  # scatter_gather | psum
    compression_device: str = "auto"  # auto | device | host

    @property
    def size(self) -> int:
        return self.num_worker

    @property
    def is_distributed(self) -> bool:
        """Distributed mode engages the PS path (global.cc:149-152): more
        than one worker, or BYTEPS_FORCE_DISTRIBUTED for the single-worker
        fake-cluster test topology."""
        return self.num_worker > 1 or self.force_distributed

    @property
    def is_root(self) -> bool:
        """Local root does the PS networking (global.cc:286-287).  The
        reference picks the *highest* local rank as root
        (communicator.cc:94)."""
        return self.local_rank == self.local_size - 1

    @staticmethod
    def from_env() -> "Config":
        return Config(
            role=_env_str("DMLC_ROLE", "worker"),
            num_worker=_env_int("DMLC_NUM_WORKER", 1),
            num_server=_env_int("DMLC_NUM_SERVER", 0),
            worker_id=_env_int("DMLC_WORKER_ID", 0),
            ps_root_uri=_env_str("DMLC_PS_ROOT_URI", "127.0.0.1"),
            ps_root_port=_env_int("DMLC_PS_ROOT_PORT", 9000),
            node_host=_env_str("DMLC_NODE_HOST", ""),
            local_rank=_env_int("BYTEPS_LOCAL_RANK", 0),
            local_size=_env_int("BYTEPS_LOCAL_SIZE", 1),
            global_rank=(
                int(os.environ["BYTEPS_GLOBAL_RANK"])
                if os.environ.get("BYTEPS_GLOBAL_RANK")
                else None
            ),
            partition_bytes=_env_int("BYTEPS_PARTITION_BYTES", 4096000),
            scheduling_credit=_env_int("BYTEPS_SCHEDULING_CREDIT", 0),
            scheduling=os.environ.get("BYTEPS_SCHEDULING", "priority"),
            min_compress_bytes=_env_int("BYTEPS_MIN_COMPRESS_BYTES", 65536),
            threadpool_size=_env_int("BYTEPS_THREADPOOL_SIZE", 4),
            compression_auto=_env_bool("BYTEPS_COMPRESSION_AUTO"),
            compression_auto_ratio=float(
                os.environ.get("BYTEPS_COMPRESSION_AUTO_RATIO", "0.9")
                or "0.9"
            ),
            compression_auto_rounds=max(
                1, _env_int("BYTEPS_COMPRESSION_AUTO_ROUNDS", 3)
            ),
            fusion_threshold=max(0, _env_int("BYTEPS_FUSION_THRESHOLD", 0)),
            fusion_bytes=max(1, _env_int("BYTEPS_FUSION_BYTES", 262144)),
            fusion_cycle_ms=max(0.0, float(
                os.environ.get("BYTEPS_FUSION_CYCLE_MS", "2") or "2"
            )),
            key_hash_fn=_env_str("BYTEPS_KEY_HASH_FN", "djb2"),
            enable_mixed_mode=_env_bool("BYTEPS_ENABLE_MIXED_MODE"),
            mixed_mode_bound=_env_int("BYTEPS_MIXED_MODE_BOUND", 101),
            built_in_hash_coef=_env_int("BYTEPS_BUILT_IN_HASH_COEF", 1),
            server_engine_threads=_env_int("BYTEPS_SERVER_ENGINE_THREAD", 4),
            server_enable_schedule=_env_bool("BYTEPS_SERVER_ENABLE_SCHEDULE"),
            enable_async=_env_bool("BYTEPS_ENABLE_ASYNC"),
            job_id=min(
                (1 << 16) - 1, max(0, _env_int("BYTEPS_JOB_ID", 0))
            ),
            job_priority=max(1, _env_int("BYTEPS_JOB_PRIORITY", 1)),
            job_quota_mbps=max(0.0, float(
                os.environ.get("BYTEPS_JOB_QUOTA_MBPS", "0") or "0"
            )),
            job_credit_bytes=max(0, _env_int("BYTEPS_JOB_CREDIT_BYTES", 0)),
            async_mode=_env_bool("BYTEPS_ASYNC"),
            staleness_bound=max(-1, _env_int("BYTEPS_STALENESS_BOUND", -1)),
            server_opt=_env_str("BYTEPS_SERVER_OPT", "").strip().lower(),
            server_opt_hp=_env_str("BYTEPS_SERVER_OPT_HP", ""),
            job_slo_s=max(0.0, float(
                os.environ.get("BYTEPS_JOB_SLO_S", "0") or "0"
            )),
            heartbeat_interval=float(
                os.environ.get("BYTEPS_HEARTBEAT_INTERVAL", "5") or "5"
            ),
            dead_node_timeout_s=float(
                os.environ.get("BYTEPS_DEAD_NODE_TIMEOUT_S", "0") or "0"
            ),
            sched_reconnect_retries=max(
                0, _env_int("BYTEPS_SCHED_RECONNECT_RETRIES", 20)
            ),
            sched_reconnect_backoff_s=float(
                os.environ.get("BYTEPS_SCHED_RECONNECT_BACKOFF_S", "0.5")
                or "0.5"
            ),
            sched_rejoin_window_s=float(
                os.environ.get("BYTEPS_SCHED_REJOIN_WINDOW_S", "15") or "15"
            ),
            rpc_retries=max(0, _env_int("BYTEPS_RPC_RETRIES", 2)),
            rpc_deadline_s=float(
                os.environ.get("BYTEPS_RPC_DEADLINE_S", "0") or "0"
            ),
            rpc_backoff_s=float(
                os.environ.get("BYTEPS_RPC_BACKOFF_S", "0.1") or "0.1"
            ),
            init_deadline_s=float(
                os.environ.get("BYTEPS_INIT_DEADLINE_S", "0") or "0"
            ),
            degraded_step_retries=max(
                0, _env_int("BYTEPS_DEGRADED_STEP_RETRIES", 0)
            ),
            journal_rounds=max(0, _env_int("BYTEPS_JOURNAL_ROUNDS", 2)),
            journal_bytes=max(1, _env_int("BYTEPS_JOURNAL_BYTES", 64 << 20)),
            resync_deadline_s=float(
                os.environ.get("BYTEPS_RESYNC_DEADLINE_S", "5") or "5"
            ),
            elastic_reshard=_env_bool("BYTEPS_ELASTIC_RESHARD"),
            ring_vnodes=max(1, _env_int("BYTEPS_RING_VNODES", 64)),
            migrate_deadline_s=float(
                os.environ.get("BYTEPS_MIGRATE_DEADLINE_S", "10") or "10"
            ),
            tcp_streams=max(1, _env_int("BYTEPS_TCP_STREAMS", 1)),
            native_client=_env_bool("BYTEPS_NATIVE_CLIENT"),
            flight_steps=max(0, _env_int("BYTEPS_FLIGHT_STEPS", 256)),
            flight_slow_factor=max(1.1, float(
                os.environ.get("BYTEPS_FLIGHT_SLOW_FACTOR", "3") or "3"
            )),
            flight_stall_s=max(0.001, float(
                os.environ.get("BYTEPS_FLIGHT_STALL_S", "5") or "5"
            )),
            flight_dir=_env_str("BYTEPS_FLIGHT_DIR", ""),
            flight_upload=_env_bool("BYTEPS_FLIGHT_UPLOAD"),
            flight_bundle_s=max(0.0, float(
                os.environ.get("BYTEPS_FLIGHT_BUNDLE_S", "60") or "60"
            )),
            log_level=_env_str("BYTEPS_LOG_LEVEL", "WARNING"),
            trace_on=_env_bool("BYTEPS_TRACE_ON"),
            trace_start_step=_env_int("BYTEPS_TRACE_START_STEP", 10),
            trace_end_step=_env_int("BYTEPS_TRACE_END_STEP", 20),
            trace_dir=_env_str("BYTEPS_TRACE_DIR", "."),
            trace_spans=_env_bool("BYTEPS_TRACE_SPANS", True),
            telemetry_on=_env_bool("BYTEPS_TELEMETRY_ON"),
            metrics_port=max(0, _env_int("BYTEPS_METRICS_PORT", 0)),
            force_distributed=_env_bool("BYTEPS_FORCE_DISTRIBUTED"),
            debug_sample_tensor=_env_str("BYTEPS_DEBUG_SAMPLE_TENSOR", ""),
            mesh_shape=_env_str("BYTEPS_TPU_MESH", ""),
            ici_reduce=_env_str("BYTEPS_TPU_ICI_REDUCE", "scatter_gather"),
            compression_device=_env_str("BYTEPS_TPU_COMPRESSION_DEVICE", "auto"),
        )


_config: Optional[Config] = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config.from_env()
    return _config


def reset_config() -> Config:
    """Re-read the environment (elastic resume path)."""
    global _config
    _config = Config.from_env()
    return _config


def set_config(cfg: Config) -> None:
    global _config
    _config = cfg


def clear_config() -> None:
    """Drop the cached snapshot; the next get_config() re-reads env."""
    global _config
    _config = None
