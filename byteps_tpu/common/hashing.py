"""Key→server assignment.

Behavioral parity with the reference's server-sharding hash functions
(global.cc:566-677): ``naive``, ``built_in``, ``djb2``, ``sdbm``, and
``mixed`` mode (BYTEPS_ENABLE_MIXED_MODE) which splits keys between
non-colocated (dedicated) servers and servers colocated with workers using
a load-ratio threshold.

The string-hash variants hash the *decimal string* of the key
(global.cc:606-627) so distribution properties match the reference.
"""

from __future__ import annotations

from typing import Callable, Dict, List

_MASK64 = 0xFFFFFFFFFFFFFFFF

_HASH_FNS: Dict[str, Callable[[int, int], int]] = {}


def _register(name: str):
    def deco(fn):
        _HASH_FNS[name] = fn
        return fn

    return deco


@_register("naive")
def hash_naive(key: int, coef: int = 1) -> int:
    # Hash_Naive (global.cc:598-600): fold the partition index into the
    # declared-key half before scaling, so key ranges (declared_key<<16)
    # don't all collapse to the same residue.
    return (((key >> 16) + (key % 65536)) * 9973) & _MASK64


@_register("built_in")
def hash_built_in(key: int, coef: int = 1) -> int:
    # Hash_BuiltIn (global.cc:601-604): std::hash<std::string> over str(key)
    # scaled by BYTEPS_BUILT_IN_HASH_COEF.  Python's hash() is salted; we use
    # a stable FNV-1a over the decimal string (the common libstdc++
    # implementation family) so results are reproducible across processes.
    h = 0xCBF29CE484222325
    for ch in str(key).encode():
        h ^= ch
        h = (h * 0x100000001B3) & _MASK64
    return (h * coef) & _MASK64


@_register("djb2")
def hash_djb2(key: int, coef: int = 1) -> int:
    # Hash_DJB2 (global.cc:606-616)
    h = 5381
    for ch in str(key).encode():
        h = ((h << 5) + h + ch) & _MASK64
    return h


@_register("sdbm")
def hash_sdbm(key: int, coef: int = 1) -> int:
    # Hash_SDBM (global.cc:618-627)
    h = 0
    for ch in str(key).encode():
        h = (ch + (h << 6) + (h << 16) - h) & _MASK64
    return h


def hash_mixed_mode(
    key: int, num_servers: int, num_workers: int, bound: int = 101
) -> int:
    """Hash_Mixed_Mode (global.cc:566-596).

    The first ``num_servers - num_workers`` server ranks are dedicated
    (non-colocated) servers; the rest are colocated with workers.  A
    load-balance ratio decides what fraction of the key space the dedicated
    servers absorb:

        ratio = 2·s·(w−1) / (w·(w+s) − 2·s)   with s = dedicated, w = workers

    Keys whose ``djb2(key) % bound`` falls below ``ratio·bound`` go to a
    dedicated server, the rest to colocated ones.
    """
    noncolo = num_servers - num_workers
    colo = num_workers
    if noncolo <= 0:
        raise ValueError("mixed mode needs more servers than workers")
    if bound < num_servers:
        raise ValueError(
            f"BYTEPS_MIXED_MODE_BOUND ({bound}) must be >= num_servers "
            f"({num_servers}) to cover each server"
        )
    ratio = (2.0 * noncolo * (num_workers - 1)) / (
        num_workers * (num_workers + noncolo) - 2 * noncolo
    )
    if not (0.0 <= ratio <= 1.0):
        raise ValueError(
            "more non-colocated servers than workers is not permitted in "
            "mixed mode (ratio out of [0,1])"
        )
    threshold = ratio * bound
    hash_res = hash_djb2(key) % bound
    if hash_res < threshold:
        return hash_djb2(hash_res) % noncolo
    return noncolo + (hash_djb2(hash_res) % colo)


def assign_server(
    key: int,
    num_servers: int,
    fn: str = "djb2",
    coef: int = 1,
    mixed_mode: bool = False,
    mixed_bound: int = 101,
    num_workers: int = 1,
) -> int:
    """Map a partition key to a server rank (EncodeDefaultKey,
    global.cc:628-677)."""
    if num_servers <= 0:
        raise ValueError("num_servers must be positive")
    if mixed_mode or fn == "mixed":
        return hash_mixed_mode(key, num_servers, num_workers, mixed_bound)
    if fn not in _HASH_FNS:
        raise ValueError(
            f"unsupported BYTEPS_KEY_HASH_FN {fn!r}; "
            "must be one of [naive, built_in, djb2, sdbm, mixed]"
        )
    return _HASH_FNS[fn](key, coef) % num_servers


def server_load(keys: List[int], num_servers: int, **kw) -> List[int]:
    """Per-server key counts, for the load-balance logging the reference
    emits at init (global.cc:660-667)."""
    load = [0] * num_servers
    for k in keys:
        load[assign_server(k, num_servers, **kw)] += 1
    return load
