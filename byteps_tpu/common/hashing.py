"""Key→server assignment.

Behavioral parity with the reference's server-sharding hash functions
(global.cc:566-677): ``naive``, ``built_in``, ``djb2``, ``sdbm``, and
``mixed`` mode (BYTEPS_ENABLE_MIXED_MODE) which splits keys between
non-colocated (dedicated) servers and servers colocated with workers using
a load-ratio threshold.

The string-hash variants hash the *decimal string* of the key
(global.cc:606-627) so distribution properties match the reference.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_MASK64 = 0xFFFFFFFFFFFFFFFF

_HASH_FNS: Dict[str, Callable[[int, int], int]] = {}


def _register(name: str):
    def deco(fn):
        _HASH_FNS[name] = fn
        return fn

    return deco


@_register("naive")
def hash_naive(key: int, coef: int = 1) -> int:
    # Hash_Naive (global.cc:598-600): fold the partition index into the
    # declared-key half before scaling, so key ranges (declared_key<<16)
    # don't all collapse to the same residue.
    return (((key >> 16) + (key % 65536)) * 9973) & _MASK64


@_register("built_in")
def hash_built_in(key: int, coef: int = 1) -> int:
    # Hash_BuiltIn (global.cc:601-604): std::hash<std::string> over str(key)
    # scaled by BYTEPS_BUILT_IN_HASH_COEF.  Python's hash() is salted; we use
    # a stable FNV-1a over the decimal string (the common libstdc++
    # implementation family) so results are reproducible across processes.
    h = 0xCBF29CE484222325
    for ch in str(key).encode():
        h ^= ch
        h = (h * 0x100000001B3) & _MASK64
    return (h * coef) & _MASK64


@_register("djb2")
def hash_djb2(key: int, coef: int = 1) -> int:
    # Hash_DJB2 (global.cc:606-616)
    h = 5381
    for ch in str(key).encode():
        h = ((h << 5) + h + ch) & _MASK64
    return h


@_register("sdbm")
def hash_sdbm(key: int, coef: int = 1) -> int:
    # Hash_SDBM (global.cc:618-627)
    h = 0
    for ch in str(key).encode():
        h = (ch + (h << 6) + (h << 16) - h) & _MASK64
    return h


def hash_mixed_mode(
    key: int, num_servers: int, num_workers: int, bound: int = 101
) -> int:
    """Hash_Mixed_Mode (global.cc:566-596).

    The first ``num_servers - num_workers`` server ranks are dedicated
    (non-colocated) servers; the rest are colocated with workers.  A
    load-balance ratio decides what fraction of the key space the dedicated
    servers absorb:

        ratio = 2·s·(w−1) / (w·(w+s) − 2·s)   with s = dedicated, w = workers

    Keys whose ``djb2(key) % bound`` falls below ``ratio·bound`` go to a
    dedicated server, the rest to colocated ones.
    """
    noncolo = num_servers - num_workers
    colo = num_workers
    if noncolo <= 0:
        raise ValueError("mixed mode needs more servers than workers")
    if bound < num_servers:
        raise ValueError(
            f"BYTEPS_MIXED_MODE_BOUND ({bound}) must be >= num_servers "
            f"({num_servers}) to cover each server"
        )
    ratio = (2.0 * noncolo * (num_workers - 1)) / (
        num_workers * (num_workers + noncolo) - 2 * noncolo
    )
    if not (0.0 <= ratio <= 1.0):
        raise ValueError(
            "more non-colocated servers than workers is not permitted in "
            "mixed mode (ratio out of [0,1])"
        )
    threshold = ratio * bound
    hash_res = hash_djb2(key) % bound
    if hash_res < threshold:
        return hash_djb2(hash_res) % noncolo
    return noncolo + (hash_djb2(hash_res) % colo)


def _djb2_bytes(data: bytes) -> int:
    """djb2 over raw bytes — same recurrence as :func:`hash_djb2` (which
    hashes the key's decimal string), kept separate so virtual-node
    labels hash without an int round trip."""
    h = 5381
    for ch in data:
        h = ((h << 5) + h + ch) & _MASK64
    return h


def _mix64(z: int) -> int:
    """splitmix64 finalizer — spreads a hash over the full u64 space.
    djb2 alone is USELESS as a ring coordinate: over the short strings
    involved (vnode labels, decimal keys) its values cluster in a tiny
    numeric band near the bottom of the space, so every key would sort
    past every point, wrap, and land on whichever rank owns the first
    point — one rank owns the whole key space.  The finalizer is the
    same arithmetic as wire.h ``key_stripe``'s, so the C++ engine's
    redirect check (``ring_key_hash``) stays bit-identical."""
    z = (z + 0x9E3779B97F4A7C15) & _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


def ring_key_hash(key: int) -> int:
    """A tensor key's ring coordinate: splitmix64(djb2(str(key))).
    Pinned against the live C++ twin (wire.h ``ring_key_hash`` via
    ``bps_wire_ring_hash``) in tests/test_reshard.py — workers, Python
    servers, and the native engine must agree on ownership bit-for-bit."""
    return _mix64(hash_djb2(key))


class HashRing:
    """Consistent-hash ring over a set of server RANKS.

    Each rank contributes ``vnodes`` virtual points (splitmix64-finalized
    djb2 of ``"s<rank>#<v>"``); a key is owned by the first point
    clockwise of :func:`ring_key_hash`.  Adding or removing one rank
    re-homes only the
    key ranges adjacent to that rank's points (≈ 1/n of the key space),
    which is what makes live migration a bounded window instead of a
    full re-shuffle — the property the elastic resharding plane
    (docs/robustness.md "migration flow") is built on.

    Deterministic across processes and languages: djb2 is the repo's
    stable string hash (global.cc:606-616 parity), so workers, Python
    servers, and the C++ engine (which receives the point arrays via
    ``bps_native_server_set_ownership``) all agree on ownership.
    """

    __slots__ = ("ranks", "vnodes", "_hashes", "_ranks")

    def __init__(self, ranks: Sequence[int], vnodes: int = 64) -> None:
        self.ranks: Tuple[int, ...] = tuple(sorted({int(r) for r in ranks}))
        if not self.ranks:
            raise ValueError("hash ring needs at least one server rank")
        self.vnodes = max(1, int(vnodes))
        pts = sorted(
            (_mix64(_djb2_bytes(f"s{r}#{v}".encode())), r)
            for r in self.ranks
            for v in range(self.vnodes)
        )
        self._hashes = [h for h, _ in pts]
        self._ranks = [r for _, r in pts]

    def owner(self, key: int) -> int:
        i = bisect.bisect_right(self._hashes, ring_key_hash(key))
        if i >= len(self._hashes):
            i = 0  # wrap: past the last point → first point
        return self._ranks[i]

    def points(self) -> List[Tuple[int, int]]:
        """Sorted ``(point_hash, rank)`` pairs — the serialized form the
        native engine's ownership check consumes."""
        return list(zip(self._hashes, self._ranks))


class OwnershipMap:
    """Epoch-stamped key→server-rank ownership (docs/robustness.md
    "migration flow").

    The scheduler bumps ``epoch`` on every server-set change and ships
    (epoch, ranks) in address books; workers route by it, servers ship
    each re-homed key's state to its new owner and answer stale-map
    requests with ``Op.WRONG_OWNER`` carrying the epoch.  Ownership is
    the consistent-hash ring (minimal movement) **overlaid with an
    optional per-key override table** — the autotuner's weighted ring
    override (docs/autotune.md "hot_key_rebalance"): the scheduler
    ships ``ring_overrides`` beside the map epoch, and an overridden
    key is owned by its override rank instead of its ring arc.  The
    epoch covers ring AND overrides as one versioned placement, so a
    rebalance (or its rollback) rides the exact same adopt → migrate →
    redirect plane a server-set change does.  The legacy modulo hash
    fns remain the non-elastic default routing.
    """

    __slots__ = ("epoch", "ring", "overrides")

    def __init__(self, ranks: Sequence[int], epoch: int = 0,
                 vnodes: int = 64,
                 overrides: Optional[Dict[int, int]] = None) -> None:
        self.epoch = int(epoch)
        self.ring = HashRing(ranks, vnodes=vnodes)
        rankset = set(self.ring.ranks)
        # overrides naming a rank outside this map's list are dropped —
        # a book can never route a key at a server it doesn't carry
        self.overrides: Dict[int, int] = {
            int(k): int(r) for k, r in (overrides or {}).items()
            if int(r) in rankset
        }

    @property
    def ranks(self) -> Tuple[int, ...]:
        return self.ring.ranks

    def owner(self, key: int) -> int:
        ov = self.overrides.get(int(key))
        return ov if ov is not None else self.ring.owner(key)


#: rings for fn="ring" routing, keyed by (num_servers, vnodes) — ring
#: construction is O(n·vnodes·log); routing must stay O(log)
_RING_CACHE: Dict[Tuple[int, int], HashRing] = {}
_RING_CACHE_LOCK = threading.Lock()


def _ring_for(num_servers: int, vnodes: int = 64) -> HashRing:
    key = (num_servers, vnodes)
    with _RING_CACHE_LOCK:
        ring = _RING_CACHE.get(key)
        if ring is None:
            ring = _RING_CACHE[key] = HashRing(range(num_servers), vnodes)
        return ring


def assign_server(
    key: int,
    num_servers: int,
    fn: str = "djb2",
    coef: int = 1,
    mixed_mode: bool = False,
    mixed_bound: int = 101,
    num_workers: int = 1,
    ring_vnodes: int = 64,
) -> int:
    """Map a partition key to a server rank (EncodeDefaultKey,
    global.cc:628-677).  ``fn="ring"`` selects the consistent-hash ring
    over ranks ``0..num_servers-1`` — same ownership as the elastic
    resharding plane at epoch 0, and the recommended fn whenever the
    server set can change."""
    if num_servers <= 0:
        raise ValueError("num_servers must be positive")
    if mixed_mode or fn == "mixed":
        return hash_mixed_mode(key, num_servers, num_workers, mixed_bound)
    if fn == "ring":
        return _ring_for(num_servers, ring_vnodes).owner(key)
    if fn not in _HASH_FNS:
        raise ValueError(
            f"unsupported BYTEPS_KEY_HASH_FN {fn!r}; "
            "must be one of [naive, built_in, djb2, sdbm, mixed, ring]"
        )
    return _HASH_FNS[fn](key, coef) % num_servers


def server_load(keys: List[int], num_servers: int, **kw) -> List[int]:
    """Per-server key counts, for the load-balance logging the reference
    emits at init (global.cc:660-667)."""
    load = [0] * num_servers
    for k in keys:
        load[assign_server(k, num_servers, **kw)] += 1
    return load
