"""Leveled logging (BPS_LOG / BPS_CHECK equivalents, logging.h).

Level from ``BYTEPS_LOG_LEVEL`` (TRACE|DEBUG|INFO|WARNING|ERROR|FATAL);
FATAL raises.  Thin wrapper over stdlib logging so host apps can reroute.
"""

from __future__ import annotations

import logging as _pylog
import os
import sys

TRACE = 5
_pylog.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "TRACE": TRACE,
    "DEBUG": _pylog.DEBUG,
    "INFO": _pylog.INFO,
    "WARNING": _pylog.WARNING,
    "ERROR": _pylog.ERROR,
    "FATAL": _pylog.CRITICAL,
}

class _StderrProxy:
    """Late-binding stderr: resolve ``sys.stderr`` at EMIT time, not at
    import.  A handler that captures the stream object at import keeps
    writing to whatever stderr was then — a host app (or test harness)
    that swaps ``sys.stderr`` afterwards would silently lose our logs."""

    def write(self, s):
        return sys.stderr.write(s)

    def flush(self):
        return sys.stderr.flush()


logger = _pylog.getLogger("byteps_tpu")
if not logger.handlers:
    _h = _pylog.StreamHandler(_StderrProxy())
    _h.setFormatter(
        _pylog.Formatter("[%(asctime)s] BYTEPS %(levelname)s %(message)s", "%H:%M:%S")
    )
    logger.addHandler(_h)


def apply_env_level() -> None:
    """(Re-)apply ``BYTEPS_LOG_LEVEL``.  Called at import AND at every
    runtime init: the level must track the environment the runtime was
    started under, not whichever import happened to load this module
    first (a long-lived process — or a test session — that sets the env
    var later would otherwise be stuck with the frozen level)."""
    logger.setLevel(_LEVELS.get(
        os.environ.get("BYTEPS_LOG_LEVEL", "WARNING").upper(),
        _pylog.WARNING,
    ))


apply_env_level()


def trace(msg, *a):
    logger.log(TRACE, msg, *a)


def debug(msg, *a):
    logger.debug(msg, *a)


def info(msg, *a):
    logger.info(msg, *a)


def warning(msg, *a):
    logger.warning(msg, *a)


def error(msg, *a):
    logger.error(msg, *a)


def check(cond: bool, msg: str = "") -> None:
    """BPS_CHECK: fatal on failure (logging.h)."""
    if not cond:
        logger.critical("check failed: %s", msg)
        raise AssertionError(f"BPS_CHECK failed: {msg}")
