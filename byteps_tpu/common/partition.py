"""Tensor partitioner.

Splits a flat tensor into contiguous element-range partitions of at most
``BYTEPS_PARTITION_BYTES`` bytes each, assigning each partition its own
communication key (PartitionTensor, operations.cc:140-180, 306-317).

Partitioning serves two purposes in the reference and both carry to TPU:
1. load-balancing keys across PS servers (key→server hashing, SURVEY §2.1);
2. pipelining — a large gradient's partitions flow through
   copy/compress/push/pull stages independently, overlapping transport with
   reduction.
"""

from __future__ import annotations

from typing import List

import numpy as np

from byteps_tpu.common.types import Partition
from byteps_tpu.common.registry import MAX_PARTS_PER_TENSOR, TensorContext


def partition_elements(
    num_elements: int, itemsize: int, partition_bytes: int, alignment: int = 64
) -> List[tuple]:
    """Return [(offset, length), ...] element ranges.

    Partition length is rounded so each partition's byte size (except the
    last) is ``partition_bytes`` rounded *down* to an ``alignment``-byte
    multiple — keeps every partition start aligned for vectorized host
    reducers (the reference page-aligns its shm slices, common.h:281-285).
    """
    if num_elements == 0:
        return []
    per_part = max(1, partition_bytes // itemsize)
    # keep partition boundaries aligned in bytes
    elems_per_align = max(1, alignment // itemsize)
    if per_part > elems_per_align:
        per_part = (per_part // elems_per_align) * elems_per_align
    parts = []
    off = 0
    while off < num_elements:
        ln = min(per_part, num_elements - off)
        parts.append((off, ln))
        off += ln
    if len(parts) > MAX_PARTS_PER_TENSOR:
        raise ValueError(
            f"{len(parts)} partitions exceeds the 2^16 key range per tensor "
            f"(operations.cc:306); raise BYTEPS_PARTITION_BYTES"
        )
    return parts


def partition_tensor(
    ctx: TensorContext, num_elements: int, itemsize: int, partition_bytes: int
) -> List[Partition]:
    """Build keyed partitions for a declared tensor and record them on the
    context (operations.cc:140-180)."""
    ranges = partition_elements(num_elements, itemsize, partition_bytes)
    parts = [
        Partition(key=ctx.key_for_part(i), offset=off, length=ln)
        for i, (off, ln) in enumerate(ranges)
    ]
    ctx.num_elements = num_elements
    ctx.partitions = parts
    return parts


def flatten_for_comm(arr: np.ndarray) -> np.ndarray:
    """Flatten to 1-D without copy when possible; the comm plane works on
    flat element ranges (the reference communicates raw byte buffers)."""
    return np.ascontiguousarray(arr).reshape(-1)


def validate_rowsparse(indices, values, total_rows: int):
    """Shared validation/normalization for the row-sparse paths
    (kRowSparsePushPull, common.h:267-271) — the engine submit and the
    api's non-distributed shortcut must agree exactly, or 1-worker and
    N-worker runs would diverge.  Returns (idx int64[n], vals f32[n, r])."""
    import numpy as _np

    idx = _np.ascontiguousarray(_np.asarray(indices, dtype=_np.int64))
    vals = _np.ascontiguousarray(_np.asarray(values, dtype=_np.float32))
    if idx.ndim != 1 or vals.ndim != 2 or vals.shape[0] != idx.shape[0]:
        raise ValueError(
            f"rowsparse wants indices (n,), values (n, row_len); got "
            f"{idx.shape} / {vals.shape}"
        )
    if idx.size and (idx.min() < 0 or idx.max() >= total_rows):
        raise ValueError(f"rowsparse indices out of range [0, {total_rows})")
    return idx, vals
