"""Named-tensor registry with stable key assignment.

TPU-native equivalent of the reference's tensor declaration machinery
(global.cc:412-436, operations.cc:283-317):

- every communicated tensor is *declared* by name, receiving a monotonically
  increasing ``declared_key``;
- the key range ``declared_key << 16`` leaves room for up to 2^16 partitions
  per tensor (operations.cc:306);
- ``redeclare_all()`` replays declarations in original order so key
  assignment is stable across elastic suspend/resume generations
  (ReDeclareTensor, global.cc:431-436).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

from byteps_tpu.common.types import DataType, Partition

MAX_PARTS_PER_TENSOR = 1 << 16


@dataclasses.dataclass
class TensorContext:
    """Per-declared-tensor state (``BPSContext``, common.h:177-205)."""

    name: str
    declared_key: int
    dtype: Optional[DataType] = None
    num_elements: int = 0
    partitions: List[Partition] = dataclasses.field(default_factory=list)
    initialized: bool = False
    # compression kwargs attached at declare time
    # (ops.py:82-120 in the mxnet plugin; RegisterCompressor global.cc:438-445)
    kwargs: Dict[str, str] = dataclasses.field(default_factory=dict)
    # profiling attachment points (SURVEY §5.1)
    version: int = 0
    # PS-client server-list generation this ctx last ran its init-push
    # barrier against; a mismatch (elastic server resize) re-inits the
    # key on its new owning server before the next use
    server_generation: int = 0
    # Engine instance that last ran this ctx's init barrier: the registry
    # outlives shutdown()/init() cycles but each init() starts servers
    # with fresh stores, so a ctx from a previous engine must re-init
    # (-1 = never)
    engine_epoch: int = -1
    # multi-tenant namespace (common/tenancy.py): the job id carried in
    # the top 16 bits of every wire key this tensor communicates under.
    # Stamped at declare time from BYTEPS_JOB_ID (per-tensor overridable
    # via the byteps_job declare kwarg); job 0 keys are bit-identical to
    # the pre-tenancy layout.
    job: int = 0

    @property
    def base_key(self) -> int:
        from byteps_tpu.common.tenancy import job_key

        return job_key(self.job, self.declared_key << 16)

    def key_for_part(self, i: int) -> int:
        if i >= MAX_PARTS_PER_TENSOR:
            raise ValueError(
                f"tensor {self.name!r} would need partition index {i} "
                f">= {MAX_PARTS_PER_TENSOR}"
            )
        return self.base_key + i


class TensorRegistry:
    """Thread-safe name→context table with stable key replay."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._contexts: Dict[str, TensorContext] = {}
        self._order: List[str] = []  # declaration order for redeclare
        self._next_key = 0

    def is_declared(self, name: str) -> bool:
        with self._lock:
            return name in self._contexts

    def declare(self, name: str, **kwargs: str) -> TensorContext:
        """Declare (or fetch) a named tensor (IsTensorDeclared +
        DeclareTensor, global.cc:412-429).  The tensor's key namespace
        (its job id, docs/async.md) is fixed at first declaration:
        ``byteps_job`` in the kwargs overrides the process-wide
        ``BYTEPS_JOB_ID`` — the in-process multi-job hook tests and
        embedded fleets use."""
        with self._lock:
            ctx = self._contexts.get(name)
            if ctx is not None:
                if kwargs:
                    ctx.kwargs.update(kwargs)
                return ctx
            ctx = TensorContext(
                name=name, declared_key=self._next_key, kwargs=dict(kwargs),
                job=self._job_for(kwargs),
            )
            self._next_key += 1
            self._contexts[name] = ctx
            self._order.append(name)
            return ctx

    @staticmethod
    def _job_for(kwargs: dict) -> int:
        """Resolve a declaration's job id: explicit ``byteps_job`` kwarg
        wins, else the process config's ``BYTEPS_JOB_ID``."""
        raw = kwargs.get("byteps_job")
        if raw is not None:
            return max(0, int(raw))
        from byteps_tpu.common.config import get_config

        return get_config().job_id

    def get(self, name: str) -> TensorContext:
        with self._lock:
            return self._contexts[name]

    def contexts_in_order(self) -> List[TensorContext]:
        with self._lock:
            return [self._contexts[n] for n in self._order]

    def redeclare_all(self) -> None:
        """Replay declarations in original order after an elastic resume so
        every generation assigns identical keys (global.cc:431-436).  Clears
        runtime state (partitions, init flags) but preserves name→key."""
        with self._lock:
            order = list(self._order)
            old = self._contexts
            self._contexts = {}
            self._next_key = 0
            for name in order:
                prev = old[name]
                ctx = TensorContext(
                    name=name, declared_key=self._next_key,
                    kwargs=dict(prev.kwargs), job=prev.job,
                )
                self._next_key += 1
                self._contexts[name] = ctx
            self._order = order

    def clear(self) -> None:
        with self._lock:
            self._contexts.clear()
            self._order.clear()
            self._next_key = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._contexts)


_registry: Optional[TensorRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> TensorRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = TensorRegistry()
        return _registry


def reset_registry() -> TensorRegistry:
    global _registry
    with _registry_lock:
        _registry = TensorRegistry()
        return _registry
