"""Job-id key namespacing — the multi-tenant dimension (docs/async.md).

The ROADMAP's "millions of users" regime means many concurrent JOBS
sharing one PS fleet, not one synchronous job.  The isolation primitive
is the communication key itself: every declared tensor's keys carry the
job id in the TOP 16 BITS of the u64 wire key, so two jobs that both
declare ``"grad.layer0"`` land on disjoint server state with zero wire
changes — the key field was always 64 bits wide, and everything keyed by
it (server KeyState, the exactly-once ledger, the ownership ring, the
worker journal, resync, migration) namespaces for free.

Layout (bits, most-significant first)::

    [ job id : 16 ][ declared_key : 32 ][ partition : 16 ]

Job 0 is the default single-tenant namespace: its keys are bit-identical
to the pre-tenancy layout, so existing deployments, golden wire
fixtures, and the native C++ engine see exactly the frames they always
did.  Nonzero jobs are a Python-engine-only surface for now: the C++
server rejects job-namespaced frames with a clean ``status=1`` echo
(log-once) so a misrouted tenant fails fast instead of corrupting a
shared store (ROADMAP: native multi-tenant parity).
"""

from __future__ import annotations

#: bit position of the job id inside a wire key
JOB_SHIFT = 48
#: job ids are 16-bit: 0 (the default single-tenant namespace) .. 65535
MAX_JOB_ID = (1 << 16) - 1
#: mask selecting the tenant-free part of a key
BASE_KEY_MASK = (1 << JOB_SHIFT) - 1


def job_key(job: int, key: int) -> int:
    """Namespace ``key`` under ``job`` (identity for job 0)."""
    if not 0 <= job <= MAX_JOB_ID:
        raise ValueError(f"job id {job} outside 0..{MAX_JOB_ID}")
    if key & ~BASE_KEY_MASK:
        raise ValueError(f"key {key:#x} already carries job bits")
    return (job << JOB_SHIFT) | key


def job_of_key(key: int) -> int:
    """The job id a wire key belongs to (0 = the default namespace)."""
    return (key >> JOB_SHIFT) & MAX_JOB_ID


def base_key(key: int) -> int:
    """``key`` with the job bits stripped (the single-tenant key)."""
    return key & BASE_KEY_MASK
