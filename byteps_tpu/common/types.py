"""Core enums and small value types.

TPU-native re-design of the reference's byteps/common/common.h:
- ``DataType``       (common.h:59-72, mshadow-ordered dtype enum)
- ``QueueType``      (common.h:88-102, the 12 pipeline stages)
- ``RequestType``    (common.h:267-271)
- ``Status``         (common.h:108-160 equivalent)
- ``TensorTableEntry`` task struct (common.h:221-264)
- Cantor-pairing command encoding (common.cc:98)
- ``align()``        (common.h:281-285)

On TPU the device-side stages (NCCL reduce/broadcast, CUDA copies) collapse
into XLA-compiled collectives, but the *host* pipeline for the PS path keeps
the same staged structure so priority scheduling, tracing, and compression
have well-defined attachment points.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Optional

import numpy as np


class DataType(enum.IntEnum):
    """Wire dtype ids, mshadow-ordered for parity (common.h:59-72)."""

    FLOAT32 = 0
    FLOAT64 = 1
    FLOAT16 = 2
    UINT8 = 3
    INT32 = 4
    INT8 = 5
    INT64 = 6
    # TPU-native addition: bfloat16 is the native accumulation-friendly
    # 16-bit type on the MXU; the reference has no bf16 (CUDA-era fp16 only).
    BFLOAT16 = 7


_NP_TO_DT = {
    np.dtype(np.float32): DataType.FLOAT32,
    np.dtype(np.float64): DataType.FLOAT64,
    np.dtype(np.float16): DataType.FLOAT16,
    np.dtype(np.uint8): DataType.UINT8,
    np.dtype(np.int32): DataType.INT32,
    np.dtype(np.int8): DataType.INT8,
    np.dtype(np.int64): DataType.INT64,
}

_DT_TO_NP = {v: k for k, v in _NP_TO_DT.items()}

_DT_SIZE = {
    DataType.FLOAT32: 4,
    DataType.FLOAT64: 8,
    DataType.FLOAT16: 2,
    DataType.UINT8: 1,
    DataType.INT32: 4,
    DataType.INT8: 1,
    DataType.INT64: 8,
    DataType.BFLOAT16: 2,
}


def to_datatype(dtype: Any) -> DataType:
    """Map a numpy/jax dtype to the wire ``DataType``."""
    name = np.dtype(dtype).name if not str(dtype) == "bfloat16" else "bfloat16"
    if name == "bfloat16":
        return DataType.BFLOAT16
    try:
        return _NP_TO_DT[np.dtype(dtype)]
    except KeyError as e:
        raise TypeError(f"unsupported dtype: {dtype!r}") from e


def dtype_size(dt: DataType) -> int:
    """Bytes per element (common.cc:23-47 equivalent)."""
    return _DT_SIZE[dt]


def to_numpy_dtype(dt: DataType) -> np.dtype:
    if dt == DataType.BFLOAT16:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return _DT_TO_NP[dt]


class QueueType(enum.IntEnum):
    """Host pipeline stages, mirroring the reference's 12-stage enum
    (common.h:88-102).  On TPU:

    - REDUCE / BROADCAST are XLA reduce-scatter / all-gather over ICI
      (compiled, not host-threaded) in the pure-collective path, but remain
      explicit host stages in the PS path where only a shard per host goes
      over DCN.
    - PCIE_REDUCE has no TPU analogue (no PCIe switch hierarchy); it is kept
      in the enum for wire/trace parity but never scheduled.
    - COPYD2H / COPYH2D are jax device_get/device_put of the host shard.
    """

    COORDINATE_REDUCE = 0
    REDUCE = 1
    COPYD2H = 2
    PCIE_REDUCE = 3
    COMPRESS = 4
    PUSH = 5
    PULL = 6
    DECOMPRESS = 7
    COPYH2D = 8
    COORDINATE_PUSH = 9
    COORDINATE_BROADCAST = 10
    BROADCAST = 11
    # TPU-native addition (no reference analogue): small-tensor fusion.
    # Partitions below BYTEPS_FUSION_THRESHOLD bytes take FUSE instead of
    # PUSH — the stage packs same-server partitions into one multi-key
    # Op.FUSED frame, and the fused reply fans back out into each
    # member's PULL stage (docs/perf.md).
    FUSE = 12


QUEUE_NUM = len(QueueType)


class RequestType(enum.IntEnum):
    """PS request flavors (common.h:267-271)."""

    DEFAULT_PUSH_PULL = 0
    ROW_SPARSE_PUSH_PULL = 1
    COMPRESSED_PUSH_PULL = 2


def get_command_type(requestType: RequestType, dtype: int) -> int:
    """Cantor pairing of (request, dtype) → command id (common.cc:98)."""
    a = int(requestType)
    b = int(dtype)
    return (a + b) * (a + b + 1) // 2 + b


def decode_command_type(cmd: int) -> tuple[RequestType, int]:
    """Inverse Cantor pairing (server-side decode, server.cc:205-230)."""
    w = int(((8 * cmd + 1) ** 0.5 - 1) / 2)
    t = w * (w + 1) // 2
    b = cmd - t
    a = w - b
    return RequestType(a), b


class StatusType(enum.IntEnum):
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5
    # the data plane degraded under the operation (server lost, retries
    # exhausted, membership shrank) — retrying the STEP is safe and may
    # succeed once the cluster heals (docs/robustness.md)
    DEGRADED = 6


class DegradedError(RuntimeError):
    """A push_pull failed because the PS data plane degraded mid-flight —
    a server died or hung past its retry budget, or the membership
    changed under the operation.

    Subclasses ``RuntimeError`` so pre-existing handlers keep working.
    Resubmitting the same step is SAFE: the abandoned round was never
    published (no worker consumed it), the engine re-runs the key's
    init barrier against the healed topology on the next submit, and
    the server dedupes any replayed pushes — summation stays
    exactly-once.  ``BYTEPS_DEGRADED_STEP_RETRIES`` makes the
    synchronous API retry automatically (api.py).
    """


@dataclasses.dataclass
class Status:
    """Operation status (common.h:108-160)."""

    type: StatusType = StatusType.OK
    reason: str = ""

    @staticmethod
    def OK() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def InProgress() -> "Status":
        return Status(StatusType.IN_PROGRESS)

    @staticmethod
    def Aborted(msg: str) -> "Status":
        return Status(StatusType.ABORTED, msg)

    @staticmethod
    def Degraded(msg: str) -> "Status":
        return Status(StatusType.DEGRADED, msg)

    @staticmethod
    def PreconditionError(msg: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, msg)

    def ok(self) -> bool:
        return self.type == StatusType.OK

    def in_progress(self) -> bool:
        return self.type == StatusType.IN_PROGRESS


ALIGN_BYTES = 64


def align(size: int, alignment: int = ALIGN_BYTES) -> int:
    """Round ``size`` up to a multiple of ``alignment`` (common.h:281-285).

    The reference aligns shm buffers for AVX loads; we keep 64B alignment so
    host-side C++ reducers can use full-width vector loads.
    """
    return ((size + alignment - 1) // alignment) * alignment


@dataclasses.dataclass
class Partition:
    """One partition of a declared tensor: a contiguous [offset, offset+length)
    element range assigned its own communication key (operations.cc:306-317)."""

    key: int
    offset: int  # element offset into the flat tensor
    length: int  # element count


@dataclasses.dataclass
class TensorTableEntry:
    """One in-flight communication task for one partition
    (common.h:221-264).  Host-engine unit of scheduling."""

    tensor_name: str
    key: int
    priority: int = 0
    version: int = 0
    offset: int = 0
    length: int = 0
    total_partnum: int = 1
    queue_list: list = dataclasses.field(default_factory=list)
    # host staging buffer (numpy view of the partition)
    cpubuff: Optional[np.ndarray] = None
    # compressed payload, set by the COMPRESS stage
    compressed: Optional[bytes] = None
    callback: Optional[Callable[[Status], None]] = None
    context: Any = None
    # once-guard: a task may be failed from two racing paths (stage-thread
    # exception AND dead-connection callback); only the first wins
    failed: bool = False
    # fusion (QueueType.FUSE): the member's slice of a fused reply, set
    # when the multi-key response fans out — its PULL stage then delivers
    # locally instead of issuing a wire pull
    fused_reply: Optional[bytes] = None
    # scheduler flag: skip the ready-table gate (fusion GROUP tasks — the
    # members already passed their per-key round gates at the FUSE queue,
    # re-gating the pack under its route key would deadlock it)
    gate_exempt: bool = False
    # fusion staging accounting: True from submit (a FUSE-routed task
    # enters the engine's staged-smalls window) until the task reaches
    # the fusion buffer or dies — the engine's idle-flush check must
    # never miss a small that is still upstream of the FUSE queue
    # (in COPYD2H, or in COMPRESS on the compressed-fused pipeline)
    fuse_staged: bool = False
    # distributed tracing (docs/observability.md): the job's trace id and
    # this partition-task's span id — propagated on every framed RPC the
    # task issues, so server-side child spans join the worker timeline.
    # 0 = tracing off.
    trace_id: int = 0
    span_id: int = 0
    # stamped by ScheduledQueue.add_task on every stage entry: monotonic
    # for the stage-dwell histogram (ENQUEUE→done), wall-clock for the
    # span timeline (cross-process alignment)
    enqueued_at: float = 0.0
    enqueued_wall: float = 0.0
    # multi-tenant dimension (common/tenancy.py): the job id the task's
    # key is namespaced under — the scheduler's per-tenant weighted-fair
    # queues and per-job gate credits key on it (docs/async.md)
    job: int = 0

    def current_stage(self) -> Optional[QueueType]:
        return self.queue_list[0] if self.queue_list else None
