"""Gradient compression subsystem.

Two-level design matching the reference (docs/gradient-compression.md:9-21):
level-1 intra-node fp16/bf16 casting lives in the plugin (``Compression``
classes); level-2 aggressive inter-node compression runs on the host
staging buffer after local reduce, before PUSH — these codecs.

Codec compute prefers the native C++ library
(byteps_tpu/native/compressor.cc); every codec also has a pure-numpy
reference implementation that is bit-identical (shared xorshift128+ RNG),
mirroring the reference's test strategy of re-simulating C++ codecs in
numpy (tests/test_onebit.py etc., SURVEY §4).
"""

from byteps_tpu.compression.base import Compressor, Compression
from byteps_tpu.compression.impl import (
    OneBitCompressor,
    TopKCompressor,
    RandomKCompressor,
    DitheringCompressor,
)
from byteps_tpu.compression.error_feedback import VanillaErrorFeedback
from byteps_tpu.compression.momentum import NesterovMomentum
from byteps_tpu.compression.registry import create_compressor
