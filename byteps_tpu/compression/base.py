"""Compressor interface + level-1 plugin compression.

``Compressor`` mirrors the reference's C++ interface (compressor.h:53-127):
``compress(fp32 array) → bytes``, ``decompress(bytes, n) → fp32 array``,
plus ``sum_into`` for server-side sparse accumulation and
``update_error`` used by the error-feedback decorator.

``Compression`` mirrors the plugins' level-1 classes (torch/compression.py,
mxnet/compression.py): none / fp16 (bf16 here — the TPU-native 16-bit).
"""

from __future__ import annotations

import abc
import numpy as np


class Compressor(abc.ABC):
    """Level-2 codec operating on the flat fp32 staging buffer."""

    #: True when :meth:`wire_nbytes` is EXACT for every payload this
    #: codec will ever emit (a size-deterministic wire format), not just
    #: a worst-case bound.  Every shipped codec sets it; the base stays
    #: False so a custom codec inheriting the default fp32-size bound is
    #: never mistaken for one.  ``BYTEPS_COMPRESSION_AUTO`` uses it to
    #: compute the policy verdict at registration instead of paying
    #: probe rounds (docs/gradient-compression.md "Codec auto-selection").
    wire_static = False

    def __init__(self, size: int) -> None:
        self.size = size  # element count of the uncompressed tensor

    @abc.abstractmethod
    def compress(self, grad: np.ndarray) -> bytes:
        ...

    @abc.abstractmethod
    def decompress(self, payload: bytes, n: int) -> np.ndarray:
        ...

    def sum_into(self, payload: bytes, acc: np.ndarray) -> None:
        """Accumulate a compressed payload into a dense fp32 buffer
        (server-side SUM_RECV).  Default: densify then add."""
        acc += self.decompress(payload, acc.size)

    def wire_nbytes(self) -> int:
        """Worst-case compressed payload size in bytes — the codec wire
        formats are size-deterministic, so this is exact for every codec
        shipped.  Feeds the FUSE-stage routing decision: a compressed
        partition fuses when its WIRE size fits the fusion threshold,
        not its raw size (docs/gradient-compression.md "Compressed wire
        path").  Default: the uncompressed fp32 size (no savings
        assumed)."""
        return self.size * 4

    def update_error(self, corrected: np.ndarray, payload: bytes) -> np.ndarray:
        """e = corrected − decompress(compress(corrected)) — the
        FastUpdateError hook (error_feedback.h:46-90)."""
        return corrected - self.decompress(payload, corrected.size)


class _NoneCompression:
    def compress(self, tensor):
        return tensor, None

    def decompress(self, tensor, ctx):
        return tensor


class _Bf16Compression:
    """Level-1: cast to bfloat16 for the wire (the reference uses fp16 —
    compression.py in each plugin; bf16 is the TPU-native choice with the
    same 2x ratio and a far safer exponent range)."""

    def compress(self, tensor):
        import ml_dtypes

        t = np.asarray(tensor)
        if t.dtype == np.float32:
            return t.astype(ml_dtypes.bfloat16), t.dtype
        return t, None

    def decompress(self, tensor, ctx):
        if ctx is not None:
            return np.asarray(tensor).astype(ctx)
        return tensor


class Compression:
    """Level-1 intra-node compression selectors (API parity with
    bps.Compression.none / .fp16)."""

    none = _NoneCompression()
    fp16 = _Bf16Compression()  # name kept for API parity; bf16 on TPU
    bf16 = _Bf16Compression()
