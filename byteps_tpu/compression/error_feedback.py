"""Error-feedback decorator (error_feedback.h:46-90).

Wraps a codec: ``compress(g)`` first corrects the gradient with the
residual of the previous round (``corrected = g + e``), compresses the
corrected value, then stores the new residual
``e = corrected − decompress(compressed)``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from byteps_tpu.compression.base import Compressor


class VanillaErrorFeedback(Compressor):
    """Registered "vanilla_ef" in the reference
    (vanilla_error_feedback.h:44-58; the lr.s mmap scaling is a
    CrossBarrier-era detail — lr scaling is accepted via set_lr())."""

    def __init__(self, inner: Compressor) -> None:
        super().__init__(inner.size)
        self.inner = inner
        self.error: Optional[np.ndarray] = None
        self.lr = 1.0

    def set_lr(self, lr: float) -> None:
        self.lr = float(lr)

    def compress(self, grad: np.ndarray) -> bytes:
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        if self.error is None:
            self.error = np.zeros_like(grad)
        corrected = grad + self.lr * self.error
        payload = self.inner.compress(corrected)
        self.error = self.inner.update_error(corrected, payload)
        return payload

    def decompress(self, payload: bytes, n: int) -> np.ndarray:
        return self.inner.decompress(payload, n)

    def sum_into(self, payload: bytes, acc: np.ndarray) -> None:
        self.inner.sum_into(payload, acc)

    def wire_nbytes(self) -> int:
        return self.inner.wire_nbytes()

    @property
    def wire_static(self) -> bool:
        # EF changes the values fed to the inner codec, never the wire
        # format — size determinism delegates
        return self.inner.wire_static
