"""Codec implementations: native C++ fast path + bit-identical numpy.

Wire formats (little-endian, defined in byteps_tpu/native/compressor.cc):

    onebit:    [f32 scale][u32 packed sign words]      (bit set = negative)
    topk:      [(i32 idx, f32 val) × k]  (indices ascending)
    randomk:   [(i32 idx, f32 val) × k]  (indices from shared xorshift128+)
    dithering: [f32 norm][i8 signed level × n]
"""

from __future__ import annotations

import numpy as np

from byteps_tpu.compression.base import Compressor
from byteps_tpu.compression.rng import XorShift128Plus, seed_pair_from
from byteps_tpu.native import get_lib


def _ptr(a: np.ndarray):
    import ctypes

    return a.ctypes.data_as(ctypes.c_void_p)


class OneBitCompressor(Compressor):
    """Sign compression packed 32:1, optional L1 scaling (onebit.cc:25,
    registered "onebit_compressor")."""

    def __init__(self, size: int, scaling: bool = False) -> None:
        super().__init__(size)
        self.scaling = scaling

    wire_static = True  # [f32 scale][packed sign words]: size-deterministic

    def wire_nbytes(self) -> int:
        return 4 + 4 * ((self.size + 31) // 32)

    def compress(self, grad: np.ndarray) -> bytes:
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        n = grad.size
        lib = get_lib()
        if lib is not None:
            out = np.empty(4 + 4 * ((n + 31) // 32), dtype=np.uint8)
            ln = lib.bps_onebit_compress(_ptr(grad), n, _ptr(out), int(self.scaling))
            return out[:ln].tobytes()
        scale = np.float32(np.abs(grad).sum() / n) if self.scaling and n else np.float32(1.0)
        neg = np.signbit(grad)
        pad = (-n) % 32
        bits = np.concatenate([neg, np.zeros(pad, bool)]).reshape(-1, 32)
        words = (bits * (1 << np.arange(32, dtype=np.uint64))).sum(1).astype(np.uint32)
        return np.float32(scale).tobytes() + words.tobytes()

    def decompress(self, payload: bytes, n: int) -> np.ndarray:
        lib = get_lib()
        if lib is not None:
            buf = np.frombuffer(payload, dtype=np.uint8)
            out = np.empty(n, dtype=np.float32)
            lib.bps_onebit_decompress(_ptr(buf), n, _ptr(out))
            return out
        scale = np.frombuffer(payload[:4], dtype=np.float32)[0]
        words = np.frombuffer(payload[4:], dtype=np.uint32)
        bits = (words[:, None] >> np.arange(32, dtype=np.uint32)) & 1
        neg = bits.reshape(-1)[:n].astype(bool)
        return np.where(neg, -scale, scale).astype(np.float32)


class TopKCompressor(Compressor):
    """Largest-k (index, value) pairs (topk.cc:26)."""

    def __init__(self, size: int, k: int) -> None:
        super().__init__(size)
        self.k = max(1, min(int(k), size))

    wire_static = True  # always exactly k (idx, val) pairs

    def wire_nbytes(self) -> int:
        return 8 * self.k

    def compress(self, grad: np.ndarray) -> bytes:
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        n, k = grad.size, min(self.k, grad.size)
        lib = get_lib()
        if lib is not None:
            out = np.empty(8 * k, dtype=np.uint8)
            ln = lib.bps_topk_compress(_ptr(grad), n, k, _ptr(out))
            return out[:ln].tobytes()
        # stable sort on magnitude: equal |values| at the k-th boundary
        # select in ascending-index order, matching the native codec's
        # comparator and the device packer (lax.top_k favors low index)
        idx = np.argsort(-np.abs(grad), kind="stable")[:k]
        idx.sort()
        rec = np.empty(k, dtype=[("i", "<i4"), ("v", "<f4")])
        rec["i"] = idx
        rec["v"] = grad[idx]
        return rec.tobytes()

    def decompress(self, payload: bytes, n: int) -> np.ndarray:
        rec = np.frombuffer(payload, dtype=[("i", "<i4"), ("v", "<f4")])
        out = np.zeros(n, dtype=np.float32)
        out[rec["i"]] = rec["v"]
        return out

    def sum_into(self, payload: bytes, acc: np.ndarray) -> None:
        rec = np.frombuffer(payload, dtype=[("i", "<i4"), ("v", "<f4")])
        np.add.at(acc, rec["i"], rec["v"])


class RandomKCompressor(Compressor):
    """Random-k with shared xorshift128+ seed (randomk.cc:25): worker and
    server derive identical index draws from the declared seed."""

    def __init__(self, size: int, k: int, seed: int = 0) -> None:
        super().__init__(size)
        self.k = max(1, min(int(k), size))
        self.s0, self.s1 = seed_pair_from(seed)

    wire_nbytes = TopKCompressor.wire_nbytes
    wire_static = True

    def compress(self, grad: np.ndarray) -> bytes:
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        n, k = grad.size, min(self.k, grad.size)
        lib = get_lib()
        if lib is not None:
            out = np.empty(8 * k, dtype=np.uint8)
            ln = lib.bps_randomk_compress(_ptr(grad), n, k, self.s0, self.s1, _ptr(out))
            return out[:ln].tobytes()
        rng = XorShift128Plus(self.s0, self.s1)
        idx = (rng.fill(k) % np.uint64(n)).astype(np.int32)
        rec = np.empty(k, dtype=[("i", "<i4"), ("v", "<f4")])
        rec["i"] = idx
        rec["v"] = grad[idx]
        return rec.tobytes()

    decompress = TopKCompressor.decompress
    sum_into = TopKCompressor.sum_into


class DitheringCompressor(Compressor):
    """Stochastic quantization with linear/natural partition and max/L2
    norm (dithering.h:43-78)."""

    def __init__(
        self, size: int, k: int = 4, partition: str = "linear",
        normalize: str = "max", seed: int = 0,
    ) -> None:
        super().__init__(size)
        self.s = max(1, int(k))  # number of levels
        self.natural = 1 if partition in ("natural", "1", 1) else 0
        self.l2 = 1 if normalize in ("l2", "L2", "1", 1) else 0
        self.s0, self.s1 = seed_pair_from(seed)

    wire_static = True  # [f32 norm][i8 level x n]: size-deterministic

    def wire_nbytes(self) -> int:
        return 4 + self.size

    def compress(self, grad: np.ndarray) -> bytes:
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        n = grad.size
        lib = get_lib()
        if lib is not None:
            out = np.empty(4 + n, dtype=np.uint8)
            ln = lib.bps_dithering_compress(
                _ptr(grad), n, self.s, self.natural, self.l2,
                self.s0, self.s1, _ptr(out),
            )
            return out[:ln].tobytes()
        # numpy reference, vectorized: only the RNG stream is inherently
        # sequential (xorshift128+ recurrence, bit-matched with the C++
        # codec); all quantization math runs as float64 array ops that are
        # bit-identical to the former scalar loop
        norm = float(np.sqrt((grad.astype(np.float64) ** 2).sum())) if self.l2 \
            else float(np.abs(grad.astype(np.float64)).max(initial=0.0))
        if norm == 0.0:
            norm = 1.0
        rng = XorShift128Plus(self.s0, self.s1)
        u = rng.uniform_fill(n)
        s = self.s
        p = np.abs(grad.astype(np.float64)) / norm
        if self.natural:
            level = np.zeros(n, dtype=np.int64)
            pos = p > 0.0
            j = np.zeros(n, dtype=np.float64)
            j[pos] = np.floor(np.log2(p[pos]))
            hi_case = pos & (j >= 0)
            lo_case = pos & (j < -s)
            mid = pos & ~hi_case & ~lo_case
            level[hi_case] = s
            level[lo_case] = (p[lo_case] / (2.0 ** (-s)) > u[lo_case]).astype(np.int64)
            jm = j[mid]
            lo_b = 2.0 ** jm
            frac = (p[mid] - lo_b) / (2.0 ** (jm + 1) - lo_b)
            level[mid] = (s + jm).astype(np.int64) + (frac > u[mid])
        else:
            scaled = p * s
            fl = np.floor(scaled)
            level = (fl + ((scaled - fl) > u)).astype(np.int64)
            np.minimum(level, s, out=level)
        levels = np.where(np.signbit(grad), -level, level).astype(np.int8)
        return np.float32(norm).tobytes() + levels.tobytes()

    def decompress(self, payload: bytes, n: int) -> np.ndarray:
        lib = get_lib()
        if lib is not None:
            buf = np.frombuffer(payload, dtype=np.uint8)
            out = np.empty(n, dtype=np.float32)
            lib.bps_dithering_decompress(_ptr(buf), n, self.s, self.natural, _ptr(out))
            return out
        norm = np.frombuffer(payload[:4], dtype=np.float32)[0]
        levels = np.frombuffer(payload[4:4 + n], dtype=np.int8).astype(np.int32)
        a = np.abs(levels)
        if self.natural:
            mag = np.where(a == 0, 0.0, 2.0 ** (a.astype(np.float64) - self.s))
        else:
            mag = a.astype(np.float64) / self.s
        return (np.sign(levels) * mag * norm).astype(np.float32)
