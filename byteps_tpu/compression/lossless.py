"""Lossless wire-frame compression (the ZipCCL-style byte layer).

Lossy gradient codecs (onebit/topk/dithering) are off the table for the
bit-exactness-critical control-plane payloads — MIGRATE_STATE bodies,
RESYNC_STATE snapshots, checkpoint shards, server-side optimizer-slot
blocks — yet those are exactly the frames that ship megabytes of highly
compressible float/JSON bytes during a reshard.  This module is the
byte-oriented LZ layer for that traffic:

- a self-describing **container** (magic + version + method + raw length)
  so any decoder can validate before touching the body, and a ``store``
  method so compression never inflates a frame;
- a deterministic greedy **LZ codec** (LZ4-block-style token stream:
  literal/match nibbles with 255-continuation, 2-byte little-endian
  offsets, MINMATCH 4) implemented twice — pure Python here and bit-
  identical C in ``native/wire.h`` — the same two-engine strategy the
  gradient codecs use, so the Python worker, the C++ server, and the
  golden fixtures can never drift;
- **fail-closed decode**: any truncation, bad offset, length mismatch, or
  unknown method raises :class:`LosslessError`; a corrupted frame is
  dropped and retried, never installed.

On the wire the transform is carried by the 0x20 status bit
(``transport.LOSSLESS_FLAG`` / ``wire.h kLosslessFlag``) — a bit no
pre-lossless decoder ever sets or strips, so old receivers see a nonzero
status and refuse the frame cleanly instead of mis-parsing the body.
"""

from __future__ import annotations

import math
import os
from typing import Optional

#: container = MAGIC(4) VERSION(1) METHOD(1) RAW_LEN(4, big-endian)
MAGIC = b"\xb5LZ0"
VERSION = 1
METHOD_STORE = 0   # body is the raw bytes verbatim
METHOD_LZ = 1      # body is an LZ token stream (format below)
HEADER_SIZE = 10

#: payloads below this never win after the 10-byte container — skip the
#: compressor entirely (mirrored by wire.h kLosslessMinBytes)
MIN_BYTES = 64

_MINMATCH = 4
_HASH_BITS = 13            # 8192-slot table, single-probe
_HASH_MULT = 2654435761    # Knuth multiplicative hash (fits u32)
_MAX_OFFSET = 65535


class LosslessError(ValueError):
    """A lossless frame failed to decode (truncated / corrupt / unknown
    method).  Like :class:`~byteps_tpu.comm.transport.ChecksumError` it is
    raised only after the frame is fully consumed off the stream, so the
    receiver drops the frame and keeps reading — fail closed, never a
    silent wrong-bytes install."""

    def __init__(self, reason: str, op=None) -> None:
        super().__init__(f"lossless decode failed: {reason}"
                         + (f" (op={op})" if op is not None else ""))
        self.reason = reason
        self.op = op


# --- native fast path ------------------------------------------------------
#: ctypes handles to wire.h's C implementation (None = unresolved,
#: False = lib unavailable — pure Python takes over), same lazy-resolve
#: shape as transport._resolve_crc_native
_native = None


def _resolve_native():
    global _native
    try:
        from byteps_tpu.native import get_lib

        lib = get_lib()
        if (lib is not None and hasattr(lib, "bps_wire_lossless_compress")
                and hasattr(lib, "bps_wire_lossless_decompress")):
            _native = (lib.bps_wire_lossless_compress,
                       lib.bps_wire_lossless_decompress)
        else:
            _native = False
    except Exception:  # noqa: BLE001 — any import/build issue → fallback
        _native = False
    return _native


def _hash4(v: int) -> int:
    return ((v * _HASH_MULT) & 0xFFFFFFFF) >> (32 - _HASH_BITS)


def lz_compress(src: bytes) -> bytes:
    """Greedy single-probe LZ over ``src`` → token stream (no container).

    Deterministic by construction (one hash slot, strictly-forward scan,
    ties impossible) and byte-identical to wire.h ``lossless_lz_compress``
    — change both together; tests/test_lossless.py pins the parity.
    """
    n = len(src)
    out = bytearray()
    if n < _MINMATCH:
        _emit_seq(out, src, 0, n, 0, 0)
        return bytes(out)
    table = [-1] * (1 << _HASH_BITS)
    # no match may begin in the last 12 bytes nor extend into the last 5
    # (the LZ4 end-condition that keeps the decoder's copy loops simple)
    mflimit = n - 12
    matchlimit = n - 5
    anchor = 0
    pos = 0
    while pos <= mflimit:
        h = _hash4(int.from_bytes(src[pos:pos + 4], "little"))
        cand = table[h]
        table[h] = pos
        if (cand >= 0 and pos - cand <= _MAX_OFFSET
                and src[cand:cand + 4] == src[pos:pos + 4]):
            mlen = _MINMATCH
            while (pos + mlen < matchlimit
                   and src[cand + mlen] == src[pos + mlen]):
                mlen += 1
            _emit_seq(out, src, anchor, pos - anchor, pos - cand, mlen)
            anchor = pos + mlen
            pos = anchor
        else:
            pos += 1
    _emit_seq(out, src, anchor, n - anchor, 0, 0)
    return bytes(out)


def _emit_seq(out: bytearray, src: bytes, lit_start: int, lit_len: int,
              offset: int, mlen: int) -> None:
    """One sequence: token, extended literal length, literals, and —
    unless this is the final literals-only sequence (``offset`` 0) —
    a 2-byte LE offset plus extended match length."""
    ml = mlen - _MINMATCH if offset else 0
    token = (min(lit_len, 15) << 4) | min(ml, 15)
    out.append(token)
    if lit_len >= 15:
        rem = lit_len - 15
        while rem >= 255:
            out.append(255)
            rem -= 255
        out.append(rem)
    out += src[lit_start:lit_start + lit_len]
    if offset:
        out += offset.to_bytes(2, "little")
        if ml >= 15:
            rem = ml - 15
            while rem >= 255:
                out.append(255)
                rem -= 255
            out.append(rem)


def lz_decompress(block, raw_len: int) -> bytes:
    """Inverse of :func:`lz_compress`; validates every read/copy against
    both the input and the declared ``raw_len`` and raises
    :class:`LosslessError` on any violation."""
    src = bytes(block)
    n = len(src)
    out = bytearray()
    pos = 0
    while True:
        if pos >= n:
            raise LosslessError("truncated token stream")
        token = src[pos]
        pos += 1
        lit_len = token >> 4
        if lit_len == 15:
            while True:
                if pos >= n:
                    raise LosslessError("truncated literal length")
                b = src[pos]
                pos += 1
                lit_len += b
                if b != 255:
                    break
        if pos + lit_len > n:
            raise LosslessError("literal run past end of input")
        out += src[pos:pos + lit_len]
        pos += lit_len
        if len(out) > raw_len:
            raise LosslessError("output exceeds declared raw length")
        if pos == n:  # final literals-only sequence
            break
        if pos + 2 > n:
            raise LosslessError("truncated match offset")
        offset = int.from_bytes(src[pos:pos + 2], "little")
        pos += 2
        if offset == 0 or offset > len(out):
            raise LosslessError("match offset outside window")
        mlen = (token & 15)
        if mlen == 15:
            while True:
                if pos >= n:
                    raise LosslessError("truncated match length")
                b = src[pos]
                pos += 1
                mlen += b
                if b != 255:
                    break
        mlen += _MINMATCH
        if len(out) + mlen > raw_len:
            raise LosslessError("match run exceeds declared raw length")
        start = len(out) - offset
        for i in range(mlen):  # overlapping copies must go byte-forward
            out.append(out[start + i])
    if len(out) != raw_len:
        raise LosslessError(
            f"raw length mismatch (declared {raw_len}, got {len(out)})")
    return bytes(out)


def compress_frame(data) -> bytes:
    """``data`` → self-describing container.  Always succeeds: when the
    LZ stream would not be smaller (or the input is tiny) the body is
    stored verbatim, so a container is never more than HEADER_SIZE bytes
    larger than its input."""
    raw = bytes(data)
    blob = _native_compress(raw)
    if blob is not None:
        return blob
    head = MAGIC + bytes((VERSION,))
    if len(raw) >= MIN_BYTES:
        comp = lz_compress(raw)
        if len(comp) < len(raw):
            return (head + bytes((METHOD_LZ,))
                    + len(raw).to_bytes(4, "big") + comp)
    return head + bytes((METHOD_STORE,)) + len(raw).to_bytes(4, "big") + raw


def decompress_frame(blob, op=None) -> bytes:
    """Inverse of :func:`compress_frame`; raises :class:`LosslessError`
    (carrying ``op`` for the receiver's counter label) on any corruption."""
    buf = bytes(blob)
    if len(buf) < HEADER_SIZE:
        raise LosslessError("container shorter than header", op=op)
    if buf[:4] != MAGIC:
        raise LosslessError("bad container magic", op=op)
    if buf[4] != VERSION:
        raise LosslessError(f"unknown container version {buf[4]}", op=op)
    method = buf[5]
    raw_len = int.from_bytes(buf[6:10], "big")
    body = buf[HEADER_SIZE:]
    if method == METHOD_STORE:
        if len(body) != raw_len:
            raise LosslessError("stored body length mismatch", op=op)
        return body
    if method != METHOD_LZ:
        raise LosslessError(f"unknown method {method}", op=op)
    try:
        dec = _native_decompress(buf, raw_len)
        if dec is not None:
            return dec
        return lz_decompress(body, raw_len)
    except LosslessError as e:
        raise LosslessError(e.reason, op=op) from None


def _native_compress(raw: bytes) -> Optional[bytes]:
    """Full container via wire.h ``lossless_compress_frame`` — bit-
    identical to the pure-Python path (store-vs-LZ decision included);
    None when the lib isn't built."""
    native = _native if _native is not None else _resolve_native()
    if not native:
        return None
    import ctypes

    cap = HEADER_SIZE + len(raw) + len(raw) // 255 + 16
    out = ctypes.create_string_buffer(cap)
    n = native[0](raw, len(raw), out, cap)
    if n <= 0:
        return None
    return out.raw[:n]


def _native_decompress(blob: bytes, raw_len: int) -> Optional[bytes]:
    """Full-container decode via wire.h ``lossless_decompress_frame``;
    None when the lib isn't built, LosslessError when the C validator
    rejects the stream."""
    native = _native if _native is not None else _resolve_native()
    if not native:
        return None
    import ctypes

    out = ctypes.create_string_buffer(max(raw_len, 1))
    n = native[1](blob, len(blob), out, raw_len)
    if n != raw_len:
        raise LosslessError("native decoder rejected stream")
    return out.raw[:raw_len]


def byte_entropy(data, limit: int = 65536) -> float:
    """Shannon entropy of ``data`` in bits/byte over at most ``limit``
    leading bytes — the codec-selection signal (≈8.0 for incompressible
    float mantissas, well under the ``BYTEPS_LOSSLESS_ENTROPY`` cutoff
    for JSON/state bytes that the LZ arm recovers)."""
    buf = bytes(data[:limit]) if limit else bytes(data)
    if not buf:
        return 0.0
    counts = [0] * 256
    for b in buf:
        counts[b] += 1
    n = len(buf)
    ent = 0.0
    for c in counts:
        if c:
            p = c / n
            ent -= p * math.log2(p)
    return ent


def lossless_entropy_cutoff() -> float:
    """Entropy (bits/byte) above which the auto-tuner's lossless arm
    declines a key (``BYTEPS_LOSSLESS_ENTROPY``, default 6.0): payload
    bytes that look random compress to nothing, so the raw arm wins."""
    v = os.environ.get("BYTEPS_LOSSLESS_ENTROPY", "")
    try:
        return float(v) if v else 6.0
    except ValueError:
        return 6.0
