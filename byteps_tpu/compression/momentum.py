"""Momentum decorator (momentum.h:44-80, nesterov_momentum.cc:23).

Applied *before* error feedback on the worker only (the server build skips
momentum — compressor_registry.cc:40-56):

    m = μ·m + g
    g' = g + μ·m
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from byteps_tpu.compression.base import Compressor


class NesterovMomentum(Compressor):
    def __init__(self, inner: Compressor, mu: float = 0.9) -> None:
        super().__init__(inner.size)
        self.inner = inner
        self.mu = float(mu)
        self.m: Optional[np.ndarray] = None

    def compress(self, grad: np.ndarray) -> bytes:
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        if self.m is None:
            self.m = np.zeros_like(grad)
        self.m = self.mu * self.m + grad
        corrected = grad + self.mu * self.m
        return self.inner.compress(corrected)

    def decompress(self, payload: bytes, n: int) -> np.ndarray:
        return self.inner.decompress(payload, n)

    def sum_into(self, payload: bytes, acc: np.ndarray) -> None:
        self.inner.sum_into(payload, acc)

    def wire_nbytes(self) -> int:
        return self.inner.wire_nbytes()

    @property
    def wire_static(self) -> bool:
        return self.inner.wire_static
