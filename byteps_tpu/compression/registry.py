"""Compressor factory from kwargs dicts.

Parity with CompressorRegistry::Create (compressor_registry.cc:39-56) and
the plugin-side kwargs translation (mxnet/__init__.py:236-290): config
flows as a str→str dict with ``byteps_``-prefixed keys:

    byteps_compressor_type           onebit | topk | randomk | dithering
    byteps_compressor_onebit_scaling "True"/"False"
    byteps_compressor_k              int (count, or ratio if < 1)
    byteps_ef_type                   vanilla
    byteps_momentum_type             nesterov
    byteps_momentum_mu               float
    byteps_seed                      int (shared randomk/dithering seed)
    byteps_dithering_partition       0 (linear) | 1 (natural)
    byteps_dithering_normalize       0 (max) | 1 (l2)

Decorator chain: momentum → error-feedback → codec; the server passes
``server=True`` to skip momentum.
"""

from __future__ import annotations

from typing import Dict, Optional

from byteps_tpu.compression.base import Compressor
from byteps_tpu.compression.error_feedback import VanillaErrorFeedback
from byteps_tpu.compression.impl import (
    DitheringCompressor,
    OneBitCompressor,
    RandomKCompressor,
    TopKCompressor,
)
from byteps_tpu.compression.momentum import NesterovMomentum


def _parse_k(kwargs: Dict[str, str], size: int) -> int:
    raw = kwargs.get("byteps_compressor_k", "1")
    val = float(raw)
    if 0 < val < 1:  # ratio semantics (topk.cc:30-36)
        return max(1, int(val * size))
    return max(1, int(val))


def translate_compression_params(params: Optional[Dict]) -> Dict[str, str]:
    """User-facing ``compression_params`` dict → byteps_* declare kwargs.

    Same translation the reference's DistributedTrainer performs
    (mxnet/__init__.py:236-290): {"compressor": "onebit", "ef": "vanilla",
    "momentum": "nesterov", "k": 0.01, "scaling": True, "seed": 42,
    "partition": "natural", "normalize": "l2", "momentum_mu": 0.9}.
    """
    out: Dict[str, str] = {}
    if not params:
        return out
    if params.get("compressor"):
        out["byteps_compressor_type"] = str(params["compressor"])
    if params.get("ef"):
        out["byteps_ef_type"] = str(params["ef"])
    if params.get("momentum"):
        out["byteps_momentum_type"] = str(params["momentum"])
    if "k" in params:
        out["byteps_compressor_k"] = str(params["k"])
    if "scaling" in params:
        out["byteps_compressor_onebit_scaling"] = str(params["scaling"])
    if "seed" in params:
        out["byteps_seed"] = str(params["seed"])
    if params.get("partition"):
        out["byteps_dithering_partition"] = (
            "1" if params["partition"] in ("natural", 1, "1") else "0"
        )
    if params.get("normalize"):
        out["byteps_dithering_normalize"] = (
            "1" if params["normalize"] in ("l2", 1, "1") else "0"
        )
    if "momentum_mu" in params:
        out["byteps_momentum_mu"] = str(params["momentum_mu"])
    return out


def parse_codec_config(kwargs: Dict[str, str], size: int) -> Optional[Dict]:
    """Normalize a declared tensor's compression kwargs.

    THE single parser of the byteps_* keys and their user-facing aliases
    — shared by :func:`create_compressor` (host chains, worker + server)
    and :func:`byteps_tpu.core.device_codec.device_codec_for` (device
    adapters), so the two factories can never drift on what a config
    means.  Returns None when no compressor is configured."""
    kwargs = {str(k): str(v) for k, v in kwargs.items()}
    ctype = kwargs.get("byteps_compressor_type") or kwargs.get("compressor")
    if not ctype:
        return None
    return {
        "ctype": ctype,
        "seed": int(float(kwargs.get("byteps_seed", kwargs.get("seed", "0")))),
        "k": _parse_k(kwargs, size),
        "scaling": kwargs.get(
            "byteps_compressor_onebit_scaling", kwargs.get("scaling", "False")
        ).lower() in ("true", "1"),
        "natural": kwargs.get("byteps_dithering_partition", "0")
        in ("1", "natural"),
        "l2": kwargs.get("byteps_dithering_normalize", "0") in ("1", "l2"),
        "ef": kwargs.get("byteps_ef_type") or kwargs.get("ef") or "",
        "momentum": kwargs.get("byteps_momentum_type")
        or kwargs.get("momentum") or "",
        "momentum_mu": float(kwargs.get("byteps_momentum_mu", "0.9")),
    }


def create_compressor(
    kwargs: Dict[str, str], size: int, server: bool = False
) -> Optional[Compressor]:
    """Build the decorator chain for a declared tensor; None when no
    compressor is configured."""
    cfg = parse_codec_config(kwargs, size)
    if cfg is None:
        return None
    ctype = cfg["ctype"]

    if ctype == "onebit":
        codec: Compressor = OneBitCompressor(size, scaling=cfg["scaling"])
    elif ctype == "topk":
        codec = TopKCompressor(size, cfg["k"])
    elif ctype == "randomk":
        codec = RandomKCompressor(size, cfg["k"], seed=cfg["seed"])
    elif ctype == "dithering":
        codec = DitheringCompressor(
            size,
            k=cfg["k"],
            partition="natural" if cfg["natural"] else "linear",
            normalize="l2" if cfg["l2"] else "max",
            seed=cfg["seed"],
        )
    else:
        raise ValueError(f"unknown compressor type {ctype!r}")

    if cfg["ef"]:
        if cfg["ef"] != "vanilla":
            raise ValueError(f"unknown error-feedback type {cfg['ef']!r}")
        codec = VanillaErrorFeedback(codec)

    if not server and cfg["momentum"]:
        if cfg["momentum"] != "nesterov":
            raise ValueError(f"unknown momentum type {cfg['momentum']!r}")
        codec = NesterovMomentum(codec, mu=cfg["momentum_mu"])

    return codec
