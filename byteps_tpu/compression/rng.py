"""xorshift128+ shared-seed RNG.

Worker and server must draw identical random index/quantization sequences
(randomk's whole correctness rests on it — randomk.cc:25, utils.h RNG in
the reference; the reference tests reimplement it in numpy,
tests/utils.py:32-51).  This numpy implementation is bit-identical to
byteps_tpu/native/compressor.cc's xorshift128p.
"""

from __future__ import annotations

import numpy as np

_MASK = np.uint64(0xFFFFFFFFFFFFFFFF)
DEFAULT_S0 = 0x9E3779B97F4A7C15
DEFAULT_S1 = 0xBF58476D1CE4E5B9


class XorShift128Plus:
    def __init__(self, s0: int = DEFAULT_S0, s1: int = DEFAULT_S1) -> None:
        self.s0 = np.uint64(s0 if s0 else DEFAULT_S0)
        self.s1 = np.uint64(s1 if s1 else DEFAULT_S1)

    def next(self) -> int:
        with np.errstate(over="ignore"):
            x = self.s0
            y = self.s1
            self.s0 = y
            x = (x ^ (x << np.uint64(23))) & _MASK
            self.s1 = x ^ y ^ (x >> np.uint64(17)) ^ (y >> np.uint64(26))
            return int((self.s1 + y) & _MASK)

    def uniform(self) -> float:
        """[0,1) double with 53-bit mantissa, matching the C++ (>>11 * 2^-53)."""
        return (self.next() >> 11) * (1.0 / 9007199254740992.0)


def seed_pair_from(seed: int) -> tuple:
    """Derive a (s0, s1) pair from a single integer seed (splitmix-style)."""
    if not seed:
        return DEFAULT_S0, DEFAULT_S1
    z = (seed + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    s0 = (z ^ (z >> 27)) or DEFAULT_S0
    z = (z + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    s1 = (z ^ (z >> 27)) or DEFAULT_S1
    return s0, s1
