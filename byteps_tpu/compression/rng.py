"""xorshift128+ shared-seed RNG.

Worker and server must draw identical random index/quantization sequences
(randomk's whole correctness rests on it — randomk.cc:25, utils.h RNG in
the reference; the reference tests reimplement it in numpy,
tests/utils.py:32-51).  This numpy implementation is bit-identical to
byteps_tpu/native/compressor.cc's xorshift128p.
"""

from __future__ import annotations

import numpy as np

_MASK = np.uint64(0xFFFFFFFFFFFFFFFF)
DEFAULT_S0 = 0x9E3779B97F4A7C15
DEFAULT_S1 = 0xBF58476D1CE4E5B9

_S23 = np.uint64(23)
_S17 = np.uint64(17)
_S26 = np.uint64(26)
_B64 = np.arange(64, dtype=np.uint64)

# ---------------------------------------------------------------------------
# GF(2) jump-ahead: the state map T(s0,s1) = (s1, f(s0)^g(s1)) with
# f(x) = x' ^ (x'>>17), x' = x^(x<<23) and g(y) = y ^ (y>>26) is linear
# over GF(2)^128, so T^L composes from bit-basis images.  A map is stored
# as two uint64[128] arrays: out-s0 / out-s1 words per input basis bit
# (bits 0..63 = s0, 64..127 = s1).
# ---------------------------------------------------------------------------


def _base_map() -> tuple:
    mask = 0xFFFFFFFFFFFFFFFF

    def f(x):
        xp = (x ^ (x << 23)) & mask
        return xp ^ (xp >> 17)

    def g(y):
        return y ^ (y >> 26)

    m0 = np.empty(128, dtype=np.uint64)
    m1 = np.empty(128, dtype=np.uint64)
    for b in range(64):  # s0 basis bits: (e, 0) -> (0, f(e))
        m0[b] = 0
        m1[b] = f(1 << b)
    for b in range(64):  # s1 basis bits: (0, e) -> (e, g(e))
        m0[64 + b] = 1 << b
        m1[64 + b] = g(1 << b)
    return m0, m1


def _compose(a: tuple, bm: tuple) -> tuple:
    """Map composition out[b] = A(B[b]) — all 128 columns at once."""
    a0, a1 = a
    b0, b1 = bm
    bits0 = ((b0[:, None] >> _B64[None, :]) & np.uint64(1)).astype(bool)
    bits1 = ((b1[:, None] >> _B64[None, :]) & np.uint64(1)).astype(bool)
    z = np.uint64(0)
    out0 = np.bitwise_xor.reduce(
        np.concatenate(
            [np.where(bits0, a0[None, :64], z), np.where(bits1, a0[None, 64:], z)],
            axis=1,
        ),
        axis=1,
    )
    out1 = np.bitwise_xor.reduce(
        np.concatenate(
            [np.where(bits0, a1[None, :64], z), np.where(bits1, a1[None, 64:], z)],
            axis=1,
        ),
        axis=1,
    )
    return out0, out1


def _apply_map(m: tuple, v0: int, v1: int) -> tuple:
    m0, m1 = m
    bits = np.concatenate(
        [
            (np.uint64(v0) >> _B64) & np.uint64(1),
            (np.uint64(v1) >> _B64) & np.uint64(1),
        ]
    ).astype(bool)
    r0 = np.bitwise_xor.reduce(m0[bits]) if bits.any() else np.uint64(0)
    r1 = np.bitwise_xor.reduce(m1[bits]) if bits.any() else np.uint64(0)
    return int(r0), int(r1)


_POW_CACHE: list = []  # _POW_CACHE[i] = T^(2^i)
_JUMP_CACHE: dict = {}
_JUMP_LOCK = __import__("threading").Lock()


def _jump_map(steps: int) -> tuple:
    """T^steps by binary-power composition (cached).

    Lock-guarded: the COMPRESS/DECOMPRESS pools run different keys'
    codecs concurrently, and an unsynchronized check-then-append on the
    power table would let two cold-cache callers both append a square of
    the same entry — corrupting every later jump (and with it randomk's
    worker/server index agreement)."""
    with _JUMP_LOCK:
        m = _JUMP_CACHE.get(steps)
        if m is not None:
            return m
        if not _POW_CACHE:
            _POW_CACHE.append(_base_map())
        while (1 << len(_POW_CACHE)) <= steps:
            last = _POW_CACHE[-1]
            _POW_CACHE.append(_compose(last, last))
        acc = None
        i = 0
        s = steps
        while s:
            if s & 1:
                acc = _POW_CACHE[i] if acc is None else _compose(_POW_CACHE[i], acc)
            s >>= 1
            i += 1
        _JUMP_CACHE[steps] = acc
        return acc


class XorShift128Plus:
    def __init__(self, s0: int = DEFAULT_S0, s1: int = DEFAULT_S1) -> None:
        self.s0 = np.uint64(s0 if s0 else DEFAULT_S0)
        self.s1 = np.uint64(s1 if s1 else DEFAULT_S1)

    def next(self) -> int:
        with np.errstate(over="ignore"):
            x = self.s0
            y = self.s1
            self.s0 = y
            x = (x ^ (x << np.uint64(23))) & _MASK
            self.s1 = x ^ y ^ (x >> np.uint64(17)) ^ (y >> np.uint64(26))
            return int((self.s1 + y) & _MASK)

    def uniform(self) -> float:
        """[0,1) double with 53-bit mantissa, matching the C++ (>>11 * 2^-53)."""
        return (self.next() >> 11) * (1.0 / 9007199254740992.0)

    def fill(self, n: int) -> np.ndarray:
        """``n`` sequential draws as a uint64 array — bit-identical to
        calling :meth:`next` ``n`` times, 1–2 orders of magnitude faster.

        The recurrence is serial, but it is LINEAR over GF(2): the
        128-bit state advances by a fixed xor/shift map T, so ``T^L`` is
        computable by binary-power composition of bit-basis images
        (_jump below).  Large fills jump 256 lane-start states L steps
        apart and then step all lanes together with numpy uint64 array
        ops — n/256 vectorized iterations instead of n Python ones.
        Small fills use a plain Python-int loop (still ~7× faster than
        per-draw np.uint64 scalar stepping).  Either path leaves
        ``self.s0/s1`` exactly where ``n`` :meth:`next` calls would."""
        if n <= 0:
            return np.empty(0, dtype=np.uint64)
        if n < 4096:
            return self._fill_serial(n)
        return self._fill_lanes(n)

    def _fill_serial(self, n: int) -> np.ndarray:
        mask = 0xFFFFFFFFFFFFFFFF
        s0, s1 = int(self.s0), int(self.s1)
        out = [0] * n
        for i in range(n):
            x = s0
            y = s1
            s0 = y
            x = (x ^ (x << 23)) & mask
            s1 = x ^ y ^ (x >> 17) ^ (y >> 26)
            out[i] = (s1 + y) & mask
        self.s0 = np.uint64(s0)
        self.s1 = np.uint64(s1)
        return np.array(out, dtype=np.uint64)

    def _fill_lanes(self, n: int, lanes: int = 256) -> np.ndarray:
        L = -(-n // lanes)  # draws per lane (ceil)
        jump = _jump_map(L)
        s0s = np.empty(lanes, dtype=np.uint64)
        s1s = np.empty(lanes, dtype=np.uint64)
        v0, v1 = int(self.s0), int(self.s1)
        for k in range(lanes):
            s0s[k], s1s[k] = v0, v1
            v0, v1 = _apply_map(jump, v0, v1)
        out = np.empty((lanes, L), dtype=np.uint64)
        a, b = s0s, s1s
        with np.errstate(over="ignore"):
            for i in range(L):
                x = a ^ (a << _S23)
                nb = x ^ b ^ (x >> _S17) ^ (b >> _S26)
                out[:, i] = nb + b
                a, b = b, nb
        # exact final state: T^n applied to the INITIAL state (the lanes
        # overshoot to lanes*L draws; discarding the tail must not leave
        # the stream advanced past n)
        self.s0, self.s1 = (
            np.uint64(w) for w in _apply_map(_jump_map(n), int(self.s0), int(self.s1))
        )
        return out.reshape(-1)[:n]

    def uniform_fill(self, n: int) -> np.ndarray:
        """``n`` sequential [0,1) doubles (53-bit mantissa), bit-identical
        to ``n`` :meth:`uniform` calls."""
        return (self.fill(n) >> np.uint64(11)) * (1.0 / 9007199254740992.0)


def seed_pair_from(seed: int) -> tuple:
    """Derive a (s0, s1) pair from a single integer seed (splitmix-style)."""
    if not seed:
        return DEFAULT_S0, DEFAULT_S1
    z = (seed + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    s0 = (z ^ (z >> 27)) or DEFAULT_S0
    z = (z + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    s1 = (z ^ (z >> 27)) or DEFAULT_S1
    return s0, s1
