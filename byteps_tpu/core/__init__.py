"""Host-side runtime: global state, priority scheduler, pipeline engine,
ready-table rendezvous, telemetry, and tracing.

TPU re-design of the reference's C++ core (byteps/common/{global,core_loops,
scheduled_queue,ready_table}.cc).  The device data plane is XLA-compiled;
what remains host-side is exactly what XLA cannot see: the DCN PS hop, its
staging copies, compression, and priority ordering.
"""
