"""Closed-loop autotuner — the scheduler's adaptive control plane
(docs/autotune.md).

Every acceleration knob this repo grew — partition/fusion thresholds,
codec choice, key placement — started life as a static env var, while
the telemetry plane (flight recorder, cluster step matrix, per-server
hot-key reports) already measures exactly the signals needed to turn
them at runtime.  This module closes the loop: the scheduler hosts an
:class:`AutoTuner` (gated by ``BYTEPS_AUTOTUNE``, default off) that
consumes the cluster aggregate each sweep and ships fleet-wide
decisions to every node as a versioned ``tuning`` section in the
existing address book (epoch-stamped like the ownership map,
incarnation-fenced with the rest of the book, adopted atomically).

Three policies ship (the table in docs/autotune.md is the contract —
``tools/check_tune_rules.py`` fails tier-1 when they drift):

- ``hot_key_rebalance`` — when one server's observed load sits at or
  above ``BYTEPS_AUTOTUNE_FACTOR`` × the peer median for
  ``BYTEPS_AUTOTUNE_SWEEPS`` consecutive sweeps, its hottest keys move
  to the least-loaded peer via a **weighted ownership-ring override**
  (``ring_overrides`` in the book), executed through the PR 8 migration
  plane (``Op.MIGRATE_STATE`` shipping, ``Op.WRONG_OWNER`` chase) — no
  re-init barrier, pulls stay bitwise through the move.
- ``fusion_threshold`` — walks the fleet ``BYTEPS_FUSION_THRESHOLD``
  per the observed step mix (wire RPC pressure vs fused pack quality)
  with a hysteresis band; never turns fusion on or off (the FUSE stage
  only exists when the launch config enabled it).
- ``codec_consensus`` — promotes the worker-local
  ``BYTEPS_COMPRESSION_AUTO`` verdicts (``compression_auto_off{codec}``)
  to a cluster decision once a quorum of workers agrees, so the whole
  fleet flips a loss-making codec together instead of drifting
  per-node.

Every policy runs behind guardrails: a per-rule cooldown, a per-sweep
action budget (``BYTEPS_AUTOTUNE_BUDGET``), and a **canary window** —
each action records the cluster's median step time at apply time and,
``BYTEPS_AUTOTUNE_CANARY_SWEEPS`` sweeps later, compares the post-action
median; a regression past ``BYTEPS_AUTOTUNE_REGRESS`` rolls the action
back automatically (``tune_rollback{rule}``) and quadruples the rule's
cooldown.  Decisions and their evidence land as flight-style bundle
directories under the scheduler's ``BYTEPS_FLIGHT_DIR``.

Policies are pure functions of a *view* dict (assembled by the
scheduler from the metric aggregate, the cluster flight matrix, and the
servers' heartbeat hot-key reports), so tests drive them on synthetic
views deterministically.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

#: every shipped policy, in evaluation order (the fixed order makes the
#: per-sweep budget deterministic).  tools/check_tune_rules.py pins this
#: tuple against docs/autotune.md in both directions.
TUNE_RULES = ("hot_key_rebalance", "fusion_threshold", "codec_consensus")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


def tuner_enabled() -> bool:
    """``BYTEPS_AUTOTUNE`` truthiness — the master gate.  Off (default)
    keeps the scheduler's books byte-for-byte the legacy shape."""
    return os.environ.get("BYTEPS_AUTOTUNE", "").lower() not in (
        "", "0", "false", "no", "off"
    )


@dataclass
class TunerConfig:
    """Guardrail knobs (docs/autotune.md "Guardrails").  The structural
    bounds (fusion walk range, pack-quality bands) are deliberately NOT
    env vars — they are policy shape, overridable in tests by
    constructing the config directly."""

    interval_s: float = 1.0     # BYTEPS_AUTOTUNE_INTERVAL_S sweep cadence
    factor: float = 2.0         # BYTEPS_AUTOTUNE_FACTOR load-imbalance bar
    sweeps: int = 3             # BYTEPS_AUTOTUNE_SWEEPS consecutive-hot bar
    cooldown_s: float = 30.0    # BYTEPS_AUTOTUNE_COOLDOWN_S per rule
    canary_sweeps: int = 5      # BYTEPS_AUTOTUNE_CANARY_SWEEPS window
    regress: float = 1.3        # BYTEPS_AUTOTUNE_REGRESS rollback bar
    budget: int = 1             # BYTEPS_AUTOTUNE_BUDGET actions per sweep
    max_moves: int = 4          # BYTEPS_AUTOTUNE_MAX_MOVES keys per rebalance
    quorum: float = 0.5         # BYTEPS_AUTOTUNE_QUORUM codec-consensus share
    force: str = ""             # BYTEPS_AUTOTUNE_FORCE one-shot drill action
    bundle_dir: str = ""        # decision evidence (BYTEPS_FLIGHT_DIR)
    # structural policy shape (not env-tunable; see class docstring)
    fusion_min: int = 4096
    fusion_max: int = 4 << 20
    pack_lo: float = 1.5        # avg fused pack ≤ this → fusion is overhead
    pack_hi: float = 6.0        # avg fused pack ≥ this → packs saturate
    rpc_hi: int = 64            # per-sweep wire RPCs that count as pressure
    # dwell evidence bands (flight-matrix per-stage deltas): when the
    # matrix carries stage dwell, a walk step must also be justified in
    # TIME — counts alone can't tell a wire-bound fleet from one whose
    # steps live in COPYD2H/COMPRESS
    dwell_fuse_frac: float = 0.05  # FUSE ≥ this share of wire dwell → fusion costs real time
    dwell_wire_frac: float = 0.2   # wire stages ≥ this share of all dwell → wire-bound

    @classmethod
    def from_env(cls) -> "TunerConfig":
        return cls(
            interval_s=max(0.05, _env_float("BYTEPS_AUTOTUNE_INTERVAL_S", 1.0)),
            factor=max(1.1, _env_float("BYTEPS_AUTOTUNE_FACTOR", 2.0)),
            sweeps=max(1, _env_int("BYTEPS_AUTOTUNE_SWEEPS", 3)),
            cooldown_s=max(0.0, _env_float("BYTEPS_AUTOTUNE_COOLDOWN_S", 30.0)),
            canary_sweeps=max(1, _env_int("BYTEPS_AUTOTUNE_CANARY_SWEEPS", 5)),
            regress=max(1.01, _env_float("BYTEPS_AUTOTUNE_REGRESS", 1.3)),
            budget=max(1, _env_int("BYTEPS_AUTOTUNE_BUDGET", 1)),
            max_moves=max(1, _env_int("BYTEPS_AUTOTUNE_MAX_MOVES", 4)),
            quorum=min(1.0, max(0.0, _env_float("BYTEPS_AUTOTUNE_QUORUM", 0.5))),
            force=os.environ.get("BYTEPS_AUTOTUNE_FORCE", ""),
            bundle_dir=(
                os.environ.get("BYTEPS_FLIGHT_DIR") or "./flight_bundles"
            ),
        )


class TuningState:
    """The versioned fleet decision — what rides the book's ``tuning``
    section (plus ``ring_overrides`` beside the ownership fields).  The
    epoch bumps on every change; nodes adopt monotonically, so a
    re-broadcast or a stale book can never roll a decision back
    accidentally (only an explicit rollback action can, by bumping the
    epoch again)."""

    __slots__ = (
        "epoch", "fusion_threshold", "codec_off", "codec_lossless",
        "overrides",
    )

    def __init__(self) -> None:
        self.epoch = 0
        #: fleet fusion threshold in bytes; None = never touched (the
        #: book omits the field and workers keep their launch value)
        self.fusion_threshold: Optional[int] = None
        #: codec type names the fleet agreed to stop compressing with
        self.codec_off: List[str] = []
        #: codec type names whose raw-pushing keys the fleet agreed to
        #: ship inside the wire lossless container (the consensus
        #: policy's third arm; docs/gradient-compression.md)
        self.codec_lossless: List[str] = []
        #: key → server rank placement overrides (the weighted ring
        #: override); shipped as ``ring_overrides`` so ownership stays
        #: atomic with the map epoch
        self.overrides: Dict[int, int] = {}

    def tuning_dict(self) -> dict:
        t: dict = {"epoch": self.epoch}
        if self.fusion_threshold is not None:
            t["fusion_threshold"] = int(self.fusion_threshold)
        if self.codec_off:
            t["codec_off"] = sorted(self.codec_off)
        if self.codec_lossless:
            t["codec_lossless"] = sorted(self.codec_lossless)
        return t

    def apply_patch(self, patch: dict) -> bool:
        """Apply one action's state patch; returns True when key
        placement changed (the caller must bump the ownership-map epoch
        and let the migration plane execute the move)."""
        moved = False
        if "fusion_threshold" in patch:
            v = patch["fusion_threshold"]
            self.fusion_threshold = None if v is None else int(v)
        for name in patch.get("codec_off_add", ()):
            if name not in self.codec_off:
                self.codec_off.append(name)
        for name in patch.get("codec_off_remove", ()):
            if name in self.codec_off:
                self.codec_off.remove(name)
        for name in patch.get("codec_lossless_add", ()):
            if name not in self.codec_lossless:
                self.codec_lossless.append(name)
        for name in patch.get("codec_lossless_remove", ()):
            if name in self.codec_lossless:
                self.codec_lossless.remove(name)
        for key, rank in (patch.get("overrides_set") or {}).items():
            k = int(key)
            if self.overrides.get(k) != int(rank):
                self.overrides[k] = int(rank)
                moved = True
        for key in patch.get("overrides_del", ()):
            if self.overrides.pop(int(key), None) is not None:
                moved = True
        self.epoch += 1
        return moved


class AutoTuner:
    """The scheduler-hosted policy engine.  One :meth:`sweep` per
    ``BYTEPS_AUTOTUNE_INTERVAL_S``: evaluate due canaries (rolling back
    regressions), then the policies in ``TUNE_RULES`` order under the
    per-sweep budget.  Thread-safe: the scheduler's control threads call
    :meth:`note_hot` / :meth:`book_extras` concurrently with the sweep
    thread."""

    def __init__(
        self,
        cfg: Optional[TunerConfig] = None,
        registry=None,
        reshard: bool = False,
        now_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        self.cfg = cfg or TunerConfig.from_env()
        self.state = TuningState()
        self._registry = registry
        #: rebalance only makes sense when the migration plane is armed
        #: (BYTEPS_ELASTIC_RESHARD on the scheduler): without it clients
        #: route by the legacy hash fns and overrides cannot land
        self.reshard = bool(reshard)
        self._now = now_fn
        self._lock = threading.RLock()
        self._sweep_idx = 0
        # per-rank load accumulators fed by the servers' heartbeat hot
        # reports ({"total": bytes, "keys": [[key, bytes]...], "owned"})
        self._hot_total: Dict[int, float] = {}
        self._hot_keys: Dict[int, Dict[int, float]] = {}
        self._hot_owned: Dict[int, int] = {}
        self._hot_streak: Dict[int, int] = {}
        # guardrail state
        self._last_action: Dict[str, float] = {}
        self._cooldown_mult: Dict[str, float] = {}
        self._canaries: List[dict] = []
        self._fusion_base: Dict[str, float] = {}
        self._forced = False
        #: applied/rolled-back decision log (evidence surface for tests,
        #: bps_doctor bundles, and the demo recipe)
        self.actions: List[dict] = []
        self.rollbacks: List[dict] = []

    # --- inputs ----------------------------------------------------------

    def note_hot(self, rank: int, report: dict) -> None:
        """Fold one server's heartbeat hot-key report into the current
        sweep window.  Reports are per-beat deltas; several beats may
        land between sweeps, so totals accumulate until the sweep
        drains them."""
        if not isinstance(report, dict):
            return
        with self._lock:
            r = int(rank)
            try:
                self._hot_total[r] = self._hot_total.get(r, 0.0) + float(
                    report.get("total", 0) or 0
                )
                per = self._hot_keys.setdefault(r, {})
                for item in report.get("keys") or ():
                    key, nbytes = int(item[0]), float(item[1])
                    per[key] = per.get(key, 0.0) + nbytes
                if report.get("owned") is not None:
                    self._hot_owned[r] = int(report["owned"])
            except (TypeError, ValueError, IndexError):
                return

    def drain_hot(self) -> Tuple[Dict[int, float], Dict[int, list], Dict[int, int]]:
        """Consume the accumulated hot reports → (per-rank load bytes,
        per-rank ``[(key, bytes), ...]`` hottest-first, per-rank owned
        key counts).  The scheduler folds these into the sweep view."""
        with self._lock:
            loads = dict(self._hot_total)
            keys = {
                r: sorted(per.items(), key=lambda kv: -kv[1])
                for r, per in self._hot_keys.items()
            }
            owned = dict(self._hot_owned)
            self._hot_total.clear()
            self._hot_keys.clear()
            return loads, keys, owned

    # --- book surface ----------------------------------------------------

    def book_extras(self, live_server_ranks) -> dict:
        """The fields this tuner adds to every address book: the
        versioned ``tuning`` section (always present while the tuner is
        armed — its arrival is what tells servers to start shipping hot
        reports) and ``ring_overrides`` when any placement override is
        live.  Overrides are filtered to the book's own rank list so a
        book can never route a key at a rank it doesn't carry (an
        evicted target's overrides drop with it; the tuner prunes its
        state on the next sweep)."""
        live = {int(r) for r in (live_server_ranks or ())}
        with self._lock:
            extras: dict = {"tuning": self.tuning_dict()}
            if self.state.overrides:
                ov = {
                    str(k): int(r) for k, r in self.state.overrides.items()
                    if int(r) in live
                }
                if ov:
                    extras["ring_overrides"] = ov
        return extras

    def tuning_dict(self) -> dict:
        with self._lock:
            return self.state.tuning_dict()

    def adopt_rejoin_report(self, report: dict) -> bool:
        """Re-adopt a rejoiner's last-applied fleet tuning
        (docs/autotune.md "Rollback flow").  A REBORN scheduler's tuner
        starts empty at epoch 0; without this its first books would
        revert every live decision — workers restore launch fusion
        thresholds and every overridden key migrates home mid-training.
        The survivors carry the state: each rejoin REGISTER reports the
        tuning section (plus the ring overrides) the node last adopted,
        and the successor re-adopts the NEWEST report before emitting
        its first books.  Monotone by tuning epoch, so a live
        scheduler — whose own state is at or above anything the fleet
        ever saw — ignores every report, and racing rejoiners converge
        on the newest.  Returns True when state moved."""
        if not isinstance(report, dict):
            return False
        try:
            epoch = int(report.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            return False
        with self._lock:
            if epoch <= self.state.epoch:
                return False
            self.state.epoch = epoch
            ft = report.get("fusion_threshold")
            try:
                self.state.fusion_threshold = (
                    None if ft is None else int(ft)
                )
            except (TypeError, ValueError):
                self.state.fusion_threshold = None
            self.state.codec_off = [
                str(c) for c in (report.get("codec_off") or ())
            ]
            self.state.codec_lossless = [
                str(c) for c in (report.get("codec_lossless") or ())
            ]
            overrides: Dict[int, int] = {}
            for k, r in (report.get("ring_overrides") or {}).items():
                try:
                    overrides[int(k)] = int(r)
                except (TypeError, ValueError):
                    continue
            self.state.overrides = overrides
            return True

    # --- the sweep -------------------------------------------------------

    def sweep(self, view: dict) -> dict:
        """One control-loop iteration over the assembled cluster view.
        Returns ``{"actions", "rollbacks", "map_changed", "changed"}`` —
        the scheduler bumps the ownership-map epoch on ``map_changed``
        and re-broadcasts books on ``changed``.  Deterministic: equal
        views (and clock) produce equal decisions."""
        with self._lock:
            self._sweep_idx += 1
            applied: List[dict] = []
            rolled: List[dict] = []
            map_changed = False
            med = self._median_step(view)
            # prune overrides whose target rank left the fleet — the
            # ring (minus override) re-homes those keys; books already
            # filtered them, this just reconciles the state + epoch
            live = {int(r) for r in (view.get("server_ranks") or ())}
            if live:
                dead = [
                    k for k, r in self.state.overrides.items() if r not in live
                ]
                if dead:
                    map_changed |= self.state.apply_patch(
                        {"overrides_del": dead}
                    )
            # 1. due canaries first: a rollback must never queue behind
            # this sweep's fresh actions
            for canary in [
                c for c in self._canaries if self._sweep_idx >= c["deadline"]
            ]:
                self._canaries.remove(canary)
                base = canary.get("baseline")
                if base and med is not None and med > base * self.cfg.regress:
                    map_changed |= self._rollback(canary, med)
                    rolled.append(canary)
            # 2. the policies, fixed order, per-sweep budget
            for rule, fn in (
                ("hot_key_rebalance", self._policy_hot_key_rebalance),
                ("fusion_threshold", self._policy_fusion_threshold),
                ("codec_consensus", self._policy_codec_consensus),
            ):
                if len(applied) >= self.cfg.budget:
                    break
                if self._cooling(rule):
                    continue
                act = self._forced_action(rule, view) or fn(view)
                if act is None:
                    continue
                map_changed |= self._apply(act, med)
                applied.append(act)
            changed = bool(applied or rolled)
        return {
            "actions": applied,
            "rollbacks": rolled,
            "map_changed": map_changed,
            "changed": changed,
        }

    @staticmethod
    def _median_step(view: dict) -> Optional[float]:
        steps = [
            float(v) for v in (view.get("steps") or {}).values()
            if v is not None and v > 0
        ]
        return statistics.median(steps) if steps else None

    def _cooling(self, rule: str) -> bool:
        last = self._last_action.get(rule)
        if last is None:
            return False
        cd = self.cfg.cooldown_s * self._cooldown_mult.get(rule, 1.0)
        return self._now() - last < cd

    def _forced_action(self, rule: str, view: dict) -> Optional[dict]:
        """``BYTEPS_AUTOTUNE_FORCE="fusion_threshold=65536"`` (or
        ``codec_off=<name>``, ``codec_lossless=<name>``,
        ``move=<key>:<rank>``): apply one operator-
        scripted action on the first eligible sweep — the canary/rollback
        drill path (docs/autotune.md "Rollback flow"), also what
        ``chaos_soak --autotune`` uses to rehearse a rollback
        deterministically."""
        if self._forced or not self.cfg.force:
            return None
        k, _, v = self.cfg.force.partition("=")
        k = k.strip()
        try:
            if k == "fusion_threshold" and rule == "fusion_threshold":
                self._forced = True
                # undo = the fleet's current concrete value: tuner state
                # if set, else the workers' reported gauge — None would
                # make the rollback a fleet-wide no-op (book omits the
                # field, workers keep the forced value)
                prev_ft = self.state.fusion_threshold
                if prev_ft is None:
                    try:
                        prev_ft = int(
                            (view.get("fusion") or {}).get("threshold") or 0
                        ) or None
                    except (TypeError, ValueError):
                        prev_ft = None
                return {
                    "rule": rule,
                    "set": {"fusion_threshold": int(v)},
                    "undo": {"fusion_threshold": prev_ft},
                    "evidence": {"forced": self.cfg.force},
                }
            if k == "codec_off" and rule == "codec_consensus":
                self._forced = True
                return {
                    "rule": rule,
                    "set": {"codec_off_add": [v.strip()]},
                    "undo": {"codec_off_remove": [v.strip()]},
                    "evidence": {"forced": self.cfg.force},
                }
            if k == "codec_lossless" and rule == "codec_consensus":
                self._forced = True
                return {
                    "rule": rule,
                    "set": {"codec_lossless_add": [v.strip()]},
                    "undo": {"codec_lossless_remove": [v.strip()]},
                    "evidence": {"forced": self.cfg.force},
                }
            if k == "move" and rule == "hot_key_rebalance" and self.reshard:
                key_s, _, rank_s = v.partition(":")
                key = int(key_s)
                self._forced = True
                prev = self.state.overrides.get(key)
                undo = (
                    {"overrides_set": {key: prev}} if prev is not None
                    else {"overrides_del": [key]}
                )
                return {
                    "rule": rule,
                    "set": {"overrides_set": {key: int(rank_s)}},
                    "undo": undo,
                    "evidence": {"forced": self.cfg.force},
                }
        except (TypeError, ValueError):
            self._forced = True  # malformed: warn once, never retry
            from byteps_tpu.common import logging as bpslog

            bpslog.warning(
                "BYTEPS_AUTOTUNE_FORCE=%r is malformed — ignored",
                self.cfg.force,
            )
        return None

    # --- policies (pure: view in, action dict or None out) ---------------

    def _policy_hot_key_rebalance(self, view: dict) -> Optional[dict]:
        """One server's load ≥ factor × peer median for N consecutive
        sweeps → move its hottest keys to the least-loaded reporting
        peer.  Only ranks that ship hot reports participate (the
        Python-engine servers — the native engine cannot migrate state,
        so it is never a source or a target; docs/autotune.md)."""
        if not self.reshard:
            return None
        loads: Dict[int, float] = {
            int(r): float(v) for r, v in (view.get("server_load") or {}).items()
        }
        if len(loads) < 2:
            self._hot_streak.clear()
            return None
        hot_rank = max(loads, key=lambda r: loads[r])
        peers = [v for r, v in loads.items() if r != hot_rank]
        med = statistics.median(peers)
        if loads[hot_rank] < self.cfg.factor * max(med, 1.0):
            self._hot_streak.clear()
            return None
        streak = self._hot_streak.get(hot_rank, 0) + 1
        self._hot_streak = {hot_rank: streak}  # a new hot rank restarts
        if streak < self.cfg.sweeps:
            return None
        hot_keys = (view.get("hot_keys") or {}).get(hot_rank) or []
        target = min(
            (r for r in loads if r != hot_rank), key=lambda r: loads[r]
        )
        moves: Dict[int, int] = {}
        for key, nbytes in hot_keys:
            if len(moves) >= self.cfg.max_moves:
                break
            key = int(key)
            if self.state.overrides.get(key) == target:
                continue
            moves[key] = target
        if not moves:
            return None
        self._hot_streak.clear()
        prev_set = {
            k: self.state.overrides[k] for k in moves
            if k in self.state.overrides
        }
        undo: dict = {"overrides_del": [k for k in moves if k not in prev_set]}
        if prev_set:
            undo["overrides_set"] = prev_set
        return {
            "rule": "hot_key_rebalance",
            "set": {"overrides_set": moves},
            "undo": undo,
            "evidence": {
                "hot_rank": hot_rank,
                "hot_load": round(loads[hot_rank], 1),
                "peer_median": round(med, 1),
                "factor": self.cfg.factor,
                "streak": streak,
                "target": target,
                "moves": {str(k): r for k, r in moves.items()},
            },
        }

    def _policy_fusion_threshold(self, view: dict) -> Optional[dict]:
        """Walk the fleet fusion threshold by the observed step mix.
        Inputs are cumulative totals from the aggregate (``wire_rpc``,
        ``fused_frames``, ``fused_keys``) plus the flight matrix's
        per-stage dwell totals; this policy deltas both against the
        previous sweep.  Shrink when fusion is pure overhead (packs
        barely coalesce AND the FUSE stage dwells a real share of wire
        time), grow when wire-RPC pressure stays high while packs
        saturate (or nothing fuses at all) AND the wire stages dominate
        the pipeline's dwell; the band between is the hysteresis dead
        zone.  Fleets whose heartbeats carry no dwell (older workers)
        degrade to the count-only walk."""
        f = view.get("fusion") or {}
        cur = self.state.fusion_threshold
        if cur is None:
            try:
                cur = int(f.get("threshold") or 0)
            except (TypeError, ValueError):
                cur = 0
        if cur <= 0:
            return None  # fusion off fleet-wide: the FUSE stage doesn't exist
        deltas = {}
        for name in ("wire_rpc", "fused_frames", "fused_keys"):
            total = float(f.get(name) or 0)
            deltas[name] = max(0.0, total - self._fusion_base.get(name, 0.0))
            self._fusion_base[name] = total
        rpc, fused, keys = (
            deltas["wire_rpc"], deltas["fused_frames"], deltas["fused_keys"]
        )
        # per-stage dwell deltas (the flight-matrix evidence): where
        # the workers' step time actually WENT since the last sweep
        dw: Dict[str, float] = {}
        for stage, total in (f.get("dwell") or {}).items():
            name = "dwell." + str(stage)
            try:
                tot = float(total)
            except (TypeError, ValueError):
                continue
            dw[str(stage)] = max(0.0, tot - self._fusion_base.get(name, 0.0))
            self._fusion_base[name] = tot
        wire_d = dw.get("PUSH", 0.0) + dw.get("FUSE", 0.0)
        total_d = sum(dw.values())
        have_dwell = total_d > 0.0
        if rpc <= 0 and fused <= 0:
            return None  # idle sweep: no evidence either way
        avg_pack = keys / fused if fused else 0.0
        new = cur
        if fused and avg_pack <= self.cfg.pack_lo and rpc >= 1:
            # dwell veto: degenerate packs only justify a shrink when
            # the FUSE stage actually dwells a real share of wire time —
            # a fuser nobody waits on isn't worth a fleet-wide walk step
            if not have_dwell or wire_d <= 0.0 or (
                dw.get("FUSE", 0.0) >= self.cfg.dwell_fuse_frac * wire_d
            ):
                new = max(self.cfg.fusion_min, cur // 2)
        elif rpc >= self.cfg.rpc_hi and (
            fused == 0 or avg_pack >= self.cfg.pack_hi
        ):
            # dwell veto: RPC pressure only justifies a grow when the
            # wire stages dominate the pipeline — growing the pack size
            # of a COPYD2H/COMPRESS-bound fleet just adds latency
            if not have_dwell or (
                wire_d >= self.cfg.dwell_wire_frac * total_d
            ):
                new = min(self.cfg.fusion_max, cur * 2)
        if new == cur:
            return None
        evidence = {
            "from": cur, "to": new,
            "wire_rpc": int(rpc), "fused_frames": int(fused),
            "avg_pack": round(avg_pack, 2),
            "band": [self.cfg.pack_lo, self.cfg.pack_hi],
        }
        if have_dwell:
            evidence["dwell_wire_s"] = round(wire_d, 6)
            evidence["dwell_total_s"] = round(total_d, 6)
        return {
            "rule": "fusion_threshold",
            "set": {"fusion_threshold": new},
            # undo restores the CONCRETE pre-action value (cur), never
            # None: a None patch makes the book omit the field, which
            # workers read as "untouched" — the regressed threshold
            # would survive its own rollback
            "undo": {"fusion_threshold": cur},
            "evidence": evidence,
        }

    def _policy_codec_consensus(self, view: dict) -> Optional[dict]:
        """A quorum of workers locally disabled one codec
        (``compression_auto_off{codec}`` verdicts) → make it a fleet
        decision so the stragglers stop paying for a codec the majority
        measured as a loss.  One codec per sweep (the budget applies
        anyway); needs ≥2 workers — one worker's verdict is already
        fleet-wide.

        Third arm: workers whose entropy probe found a raw-pushing
        codec's bytes losslessly compressible vote
        ``compression_auto_lossless{codec}`` — the same quorum share
        turns the wire lossless container on fleet-wide for that
        codec's raw keys (``codec_lossless`` in the book; only codecs
        ALREADY fleet-raw or locally verdicted raw can accumulate these
        votes, so the two arms never race on one codec)."""
        try:
            nw = int(view.get("num_workers") or 0)
        except (TypeError, ValueError):
            nw = 0
        if nw < 2:
            return None
        need = max(1, math.ceil(self.cfg.quorum * nw))
        votes = view.get("codec_votes") or {}
        for name in sorted(votes):
            if name in ("?", "") or name in self.state.codec_off:
                continue
            n = int(votes[name])
            if n >= need:
                return {
                    "rule": "codec_consensus",
                    "set": {"codec_off_add": [name]},
                    "undo": {"codec_off_remove": [name]},
                    "evidence": {
                        "codec": name, "votes": n, "quorum": need,
                        "num_workers": nw,
                    },
                }
        lz_votes = view.get("codec_lossless_votes") or {}
        for name in sorted(lz_votes):
            if name in ("?", "") or name in self.state.codec_lossless:
                continue
            n = int(lz_votes[name])
            if n >= need:
                return {
                    "rule": "codec_consensus",
                    "set": {"codec_lossless_add": [name]},
                    "undo": {"codec_lossless_remove": [name]},
                    "evidence": {
                        "codec": name, "arm": "lossless",
                        "votes": n, "quorum": need, "num_workers": nw,
                    },
                }
        return None

    # --- apply / rollback ------------------------------------------------

    def _apply(self, act: dict, med: Optional[float]) -> bool:
        rule = act["rule"]
        moved = self.state.apply_patch(act["set"])
        self._last_action[rule] = self._now()
        self._bump("tune_action", rule)
        canary = {
            "rule": rule,
            "action": act,
            "sweep": self._sweep_idx,
            "deadline": self._sweep_idx + self.cfg.canary_sweeps,
            # the pre-action cluster median step time; None (no worker
            # steps observed yet) disables the rollback comparison —
            # recorded in the bundle so the absence is auditable
            "baseline": med,
            "epoch": self.state.epoch,
        }
        self._canaries.append(canary)
        self.actions.append(act)
        self._write_bundle("action", rule, {
            "action": act, "baseline_step_s": med,
            "tuning_epoch": self.state.epoch, "sweep": self._sweep_idx,
        })
        from byteps_tpu.common import logging as bpslog

        bpslog.warning(
            "autotune action %s (tuning epoch %d): %s — canary window "
            "%d sweeps, baseline step %.4fs",
            rule, self.state.epoch, act.get("evidence"),
            self.cfg.canary_sweeps, med if med is not None else -1.0,
        )
        return moved

    def _rollback(self, canary: dict, med: float) -> bool:
        rule = canary["rule"]
        moved = self.state.apply_patch(canary["action"]["undo"])
        self._bump("tune_rollback", rule)
        # a rolled-back rule earns a longer bench before its next try
        self._cooldown_mult[rule] = min(
            16.0, self._cooldown_mult.get(rule, 1.0) * 4.0
        )
        self._last_action[rule] = self._now()
        canary["post_step_s"] = med
        self.rollbacks.append(canary)
        self._write_bundle("rollback", rule, {
            "action": canary["action"],
            "baseline_step_s": canary.get("baseline"),
            "post_step_s": med,
            "regress_bar": self.cfg.regress,
            "tuning_epoch": self.state.epoch,
            "sweep": self._sweep_idx,
        })
        from byteps_tpu.common import logging as bpslog

        bpslog.warning(
            "autotune ROLLBACK %s: post-action median step %.4fs > "
            "%.4fs x %.2f — decision reverted (tuning epoch %d), "
            "cooldown x%.0f",
            rule, med, canary.get("baseline") or 0.0, self.cfg.regress,
            self.state.epoch, self._cooldown_mult[rule],
        )
        return moved

    def _bump(self, name: str, rule: str) -> None:
        if self._registry is None:
            return
        try:
            self._registry.counters.bump(name, labels={"rule": rule})
        except Exception:  # noqa: BLE001 — telemetry must not kill a sweep
            pass

    def _write_bundle(self, kind: str, rule: str, body: dict) -> None:
        """Flight-style decision evidence: one directory per decision
        under the scheduler's bundle dir, next to the nodes' uploaded
        trigger bundles — the tuner's actions and their inputs land in
        the same place the incident evidence does."""
        if not self.cfg.bundle_dir:
            return
        try:
            ts = time.strftime("%Y%m%d-%H%M%S")
            path = os.path.join(
                self.cfg.bundle_dir,
                f"{ts}-tune-{kind}-{rule}-s{self._sweep_idx}",
            )
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "decision.json"), "w") as fh:
                json.dump(
                    {"kind": kind, "rule": rule, "time": time.time(), **body},
                    fh, indent=2, default=str,
                )
        except OSError:
            pass
