"""Device-side codec adapters for the engine pipeline (VERDICT r4 #4).

The reference compresses on the CPU *after* staging the full fp32
gradient to host (compress loop, core_loops.cc:498-536).  On TPU the
order inverts — SURVEY §7 names this the genuine improvement: the Pallas/
jnp packers (ops/onebit_device.py, ops/codecs_device.py) run BEFORE the
device→host copy, so COPYD2H moves the compressed payload (32× smaller
for onebit, ~n/2k for topk, ~4× for dithering), and the pull side moves
the compressed payload host→device and decodes on device.

Wire compatibility is inherited from the device kernels (byte-identical
framing for onebit/topk; dithering's server decode never re-derives
randomness), so the SAME servers — Python or C++ — aggregate payloads
from device-compressing and host-compressing workers interchangeably.

Eligibility (`device_codec_for`):

- bare codec chains only — error-feedback/momentum are stateful *host*
  transforms of the uncompressed gradient, so chains carrying them keep
  the host path (the residual would force a full-size D2H anyway);
- onebit / topk / dithering.  randomk is host-only: its whole contract
  is replaying the server-shared sequential xorshift128+ stream
  (randomk.cc:25), which is a 128-bit serial recurrence — antithetical
  to the device's SIMD model (and needs u64 ops TPU lacks).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from byteps_tpu.compression.registry import parse_codec_config


class _DeviceOneBit:
    def __init__(self, size: int, scaling: bool) -> None:
        self.size = size
        self.scaling = scaling

    def wire_nbytes(self) -> int:
        """Exact wire payload size (f32 scale + packed sign words) — the
        fusion-threshold gauge, same contract as the host codec's
        ``Compressor.wire_nbytes``."""
        return 4 + 4 * ((self.size + 31) // 32)

    def compress(self, dev_flat) -> bytes:
        from byteps_tpu.ops.onebit_device import (
            onebit_compress_device,
            onebit_payload,
        )

        scale, words = onebit_compress_device(dev_flat, scaling=self.scaling)
        return onebit_payload(scale, words)  # the (tiny) D2H happens here

    def decompress(self, payload: bytes, n: int):
        import jax.numpy as jnp

        from byteps_tpu.ops.onebit_device import onebit_decompress_device

        scale = jnp.asarray(np.frombuffer(payload[:4], dtype=np.float32)[0])
        words = jnp.asarray(np.frombuffer(payload[4:], dtype=np.uint32))
        return onebit_decompress_device(scale, words, n)


class _DeviceTopK:
    def __init__(self, size: int, k: int) -> None:
        self.size = size
        self.k = max(1, min(int(k), size))

    def wire_nbytes(self) -> int:
        """Exact wire payload size (k × (i32 index, f32 value) pairs)."""
        return 8 * self.k

    def compress(self, dev_flat) -> bytes:
        from byteps_tpu.ops.codecs_device import (
            topk_compress_device,
            topk_payload,
        )

        idx, vals = topk_compress_device(dev_flat, self.k)
        return topk_payload(idx, vals)

    def decompress(self, payload: bytes, n: int):
        import jax.numpy as jnp

        from byteps_tpu.ops.codecs_device import topk_sum_device

        rec = np.frombuffer(payload, dtype=[("i", "<i4"), ("v", "<f4")])
        idx = jnp.asarray(np.ascontiguousarray(rec["i"]))
        vals = jnp.asarray(np.ascontiguousarray(rec["v"]))
        return topk_sum_device(idx, vals, n)


class _DeviceDithering:
    def __init__(self, size: int, s: int, natural: bool, l2: bool, seed: int) -> None:
        self.size = size
        self.s = s
        self.natural = natural
        self.l2 = l2
        self._seed = seed or 0x5EED
        self._round = 0

    def wire_nbytes(self) -> int:
        """Exact wire payload size (f32 norm + one i8 level per element)."""
        return 4 + self.size

    def compress(self, dev_flat) -> bytes:
        import jax

        from byteps_tpu.ops.codecs_device import (
            dithering_compress_device,
            dithering_payload,
        )

        # fresh fold per round: stochastic rounding must not reuse draws
        # across steps (the host codec advances its xorshift the same way)
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed), self._round)
        self._round += 1
        norm, levels = dithering_compress_device(
            dev_flat, key, s=self.s, natural=self.natural, l2=self.l2
        )
        return dithering_payload(norm, levels)

    def decompress(self, payload: bytes, n: int):
        import jax.numpy as jnp

        from byteps_tpu.ops.codecs_device import dithering_decompress_device

        norm = jnp.asarray(np.frombuffer(payload[:4], dtype=np.float32)[0])
        levels = jnp.asarray(np.frombuffer(payload[4 : 4 + n], dtype=np.int8))
        return dithering_decompress_device(
            norm, levels, s=self.s, natural=self.natural
        )


def device_codec_for(kwargs: Dict[str, str], size: int) -> Optional[object]:
    """Device adapter for a compressor config, or None → host path.

    Parsing is delegated to the registry's ``parse_codec_config`` — the
    single normalizer of byteps_* keys and aliases — so this factory and
    ``create_compressor`` can never disagree about what is configured."""
    cfg = parse_codec_config(kwargs, size)
    if cfg is None:
        return None
    if cfg["ef"] or cfg["momentum"]:
        return None  # stateful host transforms: see module docstring
    if cfg["ctype"] == "onebit":
        return _DeviceOneBit(size, cfg["scaling"])
    if cfg["ctype"] == "topk":
        return _DeviceTopK(size, cfg["k"])
    if cfg["ctype"] == "dithering":
        return _DeviceDithering(
            size, cfg["k"], cfg["natural"], cfg["l2"], cfg["seed"]
        )
    return None  # randomk (host-only by design) or unknown
