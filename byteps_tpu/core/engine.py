"""Worker-side host pipeline engine.

Re-design of the reference's stage loops (core_loops.cc) for the TPU PS
path.  On GPU the pipeline is COORDINATE→REDUCE→COPYD2H→PUSH→PULL→COPYH2D→
BROADCAST with NCCL + CUDA events; on TPU the intra-slice REDUCE/BROADCAST
are XLA collectives inside the jitted step, so the *host* pipeline is:

    COPYD2H  (device→host staging of the host-shard)
    COMPRESS (optional, spliced when a compressor is registered —
              operations.cc:199-204)
    PUSH     (DCN → PS server, priority-scheduled)
    PULL     (DCN ← PS server)
    DECOMPRESS
    COPYH2D  (host→device, then the caller's next step consumes it)

Each stage is a ScheduledQueue + worker thread; PUSH/PULL completion is
driven by PS-client callbacks, mirroring how ps-lite callbacks drive
``FinishOrProceed`` (core_loops.cc:31-137).  Priority order means small,
front-of-model gradients overtake bulky back-of-model ones — BytePS's
scheduling core idea.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from byteps_tpu.common.config import Config
from byteps_tpu.common.partition import partition_tensor, validate_rowsparse
from byteps_tpu.common.registry import get_registry
from byteps_tpu.common.types import (
    QueueType,
    RequestType,
    Status,
    TensorTableEntry,
    get_command_type,
    to_datatype,
)
from byteps_tpu.core.ready_table import ReadyTable
from byteps_tpu.core.scheduler import ScheduledQueue


class _Job:
    """One push_pull invocation: shared state across its partitions."""

    __slots__ = (
        "name", "ctx", "flat", "result", "dtype_id", "average", "handle",
        "pending", "lock", "shape", "np_dtype", "is_jax", "version", "t0",
        "rowsparse", "device_parts", "failed", "trace_id", "step_counted",
    )

    def __init__(self, name, ctx, flat, result, dtype_id, average, handle,
                 pending, shape, np_dtype, is_jax, version, rowsparse=None,
                 device_parts=None):
        self.name = name
        self.ctx = ctx
        self.flat = flat
        self.result = result
        self.dtype_id = dtype_id
        self.average = average
        self.handle = handle
        self.pending = pending
        self.lock = threading.Lock()
        self.shape = shape
        self.np_dtype = np_dtype
        self.is_jax = is_jax
        self.version = version
        self.t0 = time.time()
        # row-sparse jobs: {"push_payload": bytes, "pull_req": bytes}
        # (kRowSparsePushPull, common.h:267-271)
        self.rowsparse = rowsparse
        # device-codec jobs: offset → decoded jax.Array per partition;
        # assembled on DEVICE in _finalize (the result never round-trips
        # through the host uncompressed)
        self.device_parts = device_parts
        # set when ANY task of this job fails: the abort fence the PS
        # client checks before (re)sending — a pending retry timer from
        # an abandoned round must not replay into the re-initialized
        # next generation (its cleared dedupe ledger would re-sum it)
        self.failed = False
        # distributed tracing: one trace id per push_pull invocation;
        # every partition task's span joins it (0 = tracing off)
        self.trace_id = 0
        # once-guard for the flight recorder's step accounting: a job
        # leaves the in-flight count exactly once whether it finalized
        # or several of its tasks raced into _fail_job
        self.step_counted = False


class _FusionGroup:
    """One flushed fusion pack: member tasks + their staged payloads,
    shipped as a single multi-key Op.FUSED RPC.  Member keys are unique
    within a pack (the per-key round gate admits at most one in-flight
    round per key, and a round has one task per key)."""

    __slots__ = ("members", "done", "lock")

    def __init__(self, members: List[tuple]) -> None:
        self.members = members  # [(task, payload buffer)]
        self.done = False  # once-guard: deliver/on_error both race here
        self.lock = threading.Lock()


class _FusionBuffer:
    """Accumulating pack for one destination server."""

    __slots__ = ("members", "nbytes", "max_priority", "oldest")

    def __init__(self) -> None:
        self.members: List[tuple] = []
        self.nbytes = 0
        self.max_priority = -(1 << 62)
        self.oldest = time.monotonic()


class _Fuser:
    """Per-destination-server fusion buffers — the FUSE stage's state.

    Small partitions (≤ BYTEPS_FUSION_THRESHOLD bytes) are packed here by
    destination server instead of each paying its own framed RPC, deadline
    arm, and retry state.  Flush triggers (each bumps a
    ``fusion_flush_<reason>`` counter):

    - ``full``:  the pack reached BYTEPS_FUSION_BYTES — ship it.
    - ``idle``:  the FUSE queue drained, so no more smalls are coming from
      this burst; holding the pack any longer would only add latency.
      This keeps sequential single-tensor rounds near-zero-overhead.
    - ``cycle``: a member has waited BYTEPS_FUSION_CYCLE_MS — the latency
      backstop for workloads whose FUSE queue never quite drains.
    """

    def __init__(self, engine: "PipelineEngine") -> None:
        self._engine = engine
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        #: (destination server, job) → accumulating pack
        self._bufs: Dict[tuple, _FusionBuffer] = {}
        self._cycle_thread: Optional[threading.Thread] = None

    def add(self, task: TensorTableEntry, payload) -> None:
        # packs are keyed by (destination server, JOB): a process
        # hosting several tenants (byteps_job declare kwarg) must not
        # mix jobs in one frame — the pack competes in the WFQ, spends
        # gate credits, and is admission-metered under ONE job, so a
        # mixed pack would ride the wrong tenant's share
        bkey = (self._engine.client.server_for(task.key), task.job)
        full = None
        with self._lock:
            buf = self._bufs.get(bkey)
            if buf is None:
                buf = self._bufs[bkey] = _FusionBuffer()
                # wake the cycle thread: it sleeps indefinitely while
                # every buffer is empty, and must now arm this pack's
                # BYTEPS_FUSION_CYCLE_MS deadline
                self._cv.notify()
            buf.members.append((task, payload))
            buf.nbytes += len(payload)
            buf.max_priority = max(buf.max_priority, task.priority)
            if buf.nbytes >= self._engine.cfg.fusion_bytes:
                full = self._bufs.pop(bkey)
        if full is not None:
            self._emit(full, "full")
        self._ensure_cycle_thread()

    def drain_idle(self) -> None:
        """The FUSE queue is empty: flush every pack now."""
        with self._lock:
            bufs, self._bufs = self._bufs, {}
        for buf in bufs.values():
            self._emit(buf, "idle")

    def _ensure_cycle_thread(self) -> None:
        if self._cycle_thread is not None:
            return
        with self._lock:
            if self._cycle_thread is not None:
                return
            t = threading.Thread(
                target=self._cycle_loop, name="bps-fusion-cycle", daemon=True
            )
            self._cycle_thread = t
        t.start()

    def _cycle_loop(self) -> None:
        """BYTEPS_FUSION_CYCLE_MS backstop, event-driven: sleeps until the
        OLDEST live pack's deadline (woken by add() when a pack is born),
        not on a fixed half-cycle poll — an idle fuser costs ~2 wakeups/s,
        not a permanent kHz tick."""
        cycle_s = max(0.0005, self._engine.cfg.fusion_cycle_ms / 1e3)
        stop = self._engine._stop
        while not stop.is_set():
            aged = []
            with self._cv:
                if not self._bufs:
                    # idle: nothing to age — park until add() notifies
                    # (bounded so engine shutdown is noticed promptly)
                    self._cv.wait(0.5)
                    continue
                now = time.monotonic()
                soonest = min(b.oldest for b in self._bufs.values()) + cycle_s
                if soonest > now:
                    self._cv.wait(soonest - now)
                    continue
                for bkey in [
                    k for k, b in self._bufs.items()
                    if now - b.oldest >= cycle_s
                ]:
                    aged.append(self._bufs.pop(bkey))
            for buf in aged:
                self._emit(buf, "cycle")

    def _emit(self, buf: _FusionBuffer, reason: str) -> None:
        """Hand the pack to the PUSH queue as ONE group task.  The group
        inherits the MAX priority of its members (fusion must never defeat
        priority scheduling: a pack holding one urgent front-layer gradient
        outranks every bulkier push below that urgency) and the summed
        length (credit accounting); ``gate_exempt`` skips the per-key round
        gate the members already passed at the FUSE queue."""
        from byteps_tpu.core.telemetry import COUNT_BUCKETS, counters, metrics

        counters().bump(f"fusion_flush_{reason}")
        # pack-quality histograms (docs/observability.md): density tells
        # whether the threshold actually coalesces (p50 of 1 = fusion is
        # pure overhead), flush age is the latency the pack COST its
        # oldest member — the two knobs BYTEPS_FUSION_BYTES /
        # BYTEPS_FUSION_CYCLE_MS trade against each other
        metrics().observe(
            "fused_pack_keys", len(buf.members), buckets=COUNT_BUCKETS
        )
        metrics().observe(
            "fused_flush_age_seconds", time.monotonic() - buf.oldest
        )
        members = buf.members
        group = TensorTableEntry(
            tensor_name="<fused>",
            key=members[0][0].key,
            priority=buf.max_priority,
            version=0,
            length=sum(t.length for t, _ in members),
            total_partnum=len(members),
            queue_list=[QueueType.PUSH],
            context=_FusionGroup(members),
            gate_exempt=True,
            # members share one process (= one tenant); the pack
            # competes in the WFQ under its members' job
            job=members[0][0].job,
        )
        self._engine.queues[QueueType.PUSH].add_task(group)


class _StripedStage:
    """N parallel queues for a stage, striped by key.

    The reference offloads COMPRESS/DECOMPRESS to a thread pool
    (``BYTEPS_THREADPOOL_SIZE``, core_loops.cc:498-536); striping by key
    keeps each key's stateful EF/momentum codec on one thread so rounds of
    the same key never race while different keys compress in parallel.
    """

    def __init__(self, queue_type: QueueType, n: int) -> None:
        self.queue_type = queue_type
        self.stripes = [ScheduledQueue(queue_type) for _ in range(max(1, n))]

    def add_task(self, task: TensorTableEntry) -> None:
        self.stripes[task.key % len(self.stripes)].add_task(task)

    def report_finish(self, task: TensorTableEntry) -> None:
        self.stripes[task.key % len(self.stripes)].report_finish(task)


class PipelineEngine:
    #: host pipeline stage order (PS path); COMPRESS/DECOMPRESS spliced in
    #: when the tensor has a registered compressor (operations.cc:199-204)
    STAGES = [QueueType.COPYD2H, QueueType.PUSH, QueueType.PULL, QueueType.COPYH2D]
    STAGES_COMPRESSED = [
        QueueType.COPYD2H, QueueType.COMPRESS, QueueType.PUSH,
        QueueType.PULL, QueueType.DECOMPRESS, QueueType.COPYH2D,
    ]
    #: small partitions (≤ BYTEPS_FUSION_THRESHOLD bytes) swap PUSH for
    #: FUSE: the multi-key fused RPC carries both halves of the round
    #: trip, and the PULL stage delivers the fanned-out reply slice
    #: locally (docs/perf.md)
    STAGES_FUSED = [
        QueueType.COPYD2H, QueueType.FUSE, QueueType.PULL, QueueType.COPYH2D,
    ]
    #: compressed wire path × fusion (docs/gradient-compression.md
    #: "Compressed wire path"): a compressed partition whose WIRE size
    #: (codec wire_nbytes) fits the fusion threshold rides the fuser like
    #: any small partition — its member cmd carries
    #: RequestType.COMPRESSED_PUSH_PULL so the server sums it through the
    #: key's codec chain, and the fused reply slot comes back
    #: codec-compressed for the DECOMPRESS stage to decode.  The two
    #: headline wire optimizations finally multiply instead of excluding
    #: each other.
    STAGES_COMPRESSED_FUSED = [
        QueueType.COPYD2H, QueueType.COMPRESS, QueueType.FUSE,
        QueueType.PULL, QueueType.DECOMPRESS, QueueType.COPYH2D,
    ]
    #: device codec × fusion (docs/gradient-compression.md "Device
    #: path"): the device packer emits the exact wire encoding ON
    #: DEVICE, so COPYD2H already lands `task.compressed` — COMPRESS is
    #: a pass-through, the fuser adds the device buffer's bytes as a
    #: COMPRESSED_PUSH_PULL member, and the fused reply slot feeds the
    #: device decoder on DECOMPRESS.  Same stage sequence as the host
    #: compressed+fused path; the difference is WHERE the packing ran —
    #: only compressed bytes ever cross the D2H boundary.
    STAGES_DEVICE_COMPRESSED_FUSED = [
        QueueType.COPYD2H, QueueType.COMPRESS, QueueType.FUSE,
        QueueType.PULL, QueueType.DECOMPRESS, QueueType.COPYH2D,
    ]

    #: monotonically increasing engine-instance id: the tensor registry
    #: (and each ctx's ``initialized`` flag) outlives shutdown()/init()
    #: cycles, but servers started by a LATER init() have fresh stores —
    #: a ctx initialized under a previous engine must re-run its
    #: init-push barrier, exactly like an elastic server resize
    _epoch_counter = itertools.count()

    def __init__(self, cfg: Config, ps_client, telemetry=None, tracer=None,
                 flightrec=None) -> None:
        self.cfg = cfg
        self.client = ps_client
        self.telemetry = telemetry
        self.tracer = tracer
        # flight recorder (docs/observability.md "Flight recorder &
        # doctor"): the engine stamps one ledger record per completed
        # round — when the in-flight job count drains back to zero —
        # carrying the step wall time.  None / capacity 0 = off.
        self._flight = flightrec
        self._step_lock = threading.Lock()
        self._step_open = 0
        self._step_t0 = 0.0
        self._epoch = next(PipelineEngine._epoch_counter)
        self._stop = threading.Event()
        credit = cfg.scheduling_credit
        pool = max(1, cfg.threadpool_size)
        # PUSH round-order gate (the ReadyTable rendezvous of
        # scheduled_queue.cc:48-79, re-purposed for the single-process TPU
        # worker): counts[key] = highest round allowed to leave the PUSH
        # queue.  Concurrent jobs on one name carry caller-chosen
        # priorities, so without the gate a later round could overtake an
        # earlier round of the same key — the server aggregates per round
        # of arrivals, so cross-round interleaving corrupts sums (and a
        # reordered pair can deadlock: the later round's pull waits on a
        # round the earlier push never gets to start).  Completions advance
        # the allowance.
        self._push_ready = ReadyTable(ready_count=1, name="push")
        self._seeded: set = set()  # keys whose gate this engine has seeded
        disc = cfg.scheduling
        # per-tenant QoS in the stage queues (docs/async.md): this
        # process's job registers its weighted share, and an optional
        # per-job in-flight byte budget bounds the tenant the way the
        # global credit bounds the queue.  With one job per process
        # (the default) the WFQ layer is inert.
        from byteps_tpu.core.scheduler import set_job_weight

        set_job_weight(cfg.job_id, max(1, cfg.job_priority))
        job_credits = (
            {cfg.job_id: cfg.job_credit_bytes}
            if cfg.job_credit_bytes > 0 else None
        )
        self.queues: Dict[QueueType, Any] = {
            QueueType.COPYD2H: ScheduledQueue(QueueType.COPYD2H, discipline=disc),
            QueueType.COMPRESS: _StripedStage(QueueType.COMPRESS, pool),
            QueueType.PUSH: ScheduledQueue(
                QueueType.PUSH,
                credit_bytes=credit,
                ready_table=self._push_ready,
                version_gated=True,
                discipline=disc,
                job_credits=job_credits,
            ),
            # FUSE shares the PUSH round gate: a fused member obeys the
            # same per-key round order as an unfused push — the gate just
            # moves to where the small partition leaves the pipeline
            QueueType.FUSE: ScheduledQueue(
                QueueType.FUSE,
                ready_table=self._push_ready,
                version_gated=True,
                discipline=disc,
                job_credits=job_credits,
            ),
            QueueType.PULL: ScheduledQueue(QueueType.PULL, discipline=disc),
            QueueType.DECOMPRESS: _StripedStage(QueueType.DECOMPRESS, pool),
            QueueType.COPYH2D: ScheduledQueue(QueueType.COPYH2D, discipline=disc),
        }
        self._fuser = _Fuser(self)
        # recovery plane (docs/robustness.md "healing flow"): bounded
        # journal of emitted push payloads, replayed by the PS client's
        # resync heal when a live server reports rounds it never
        # absorbed.  (Re)configured per engine so a previous generation's
        # entries can never replay into this one's round numbering.
        from byteps_tpu.comm.journal import configure_journal

        self._journal = configure_journal(cfg.journal_rounds, cfg.journal_bytes)
        # small tasks submitted but not yet handed to the fusion buffer:
        # the idle-flush decision needs this because queue.pending() can't
        # see a task COPYD2H has popped but not finished staging
        self._staged_smalls = 0
        self._fuse_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._init_lock = threading.Lock()
        # per-key stateful codec chains (per-partition compressor
        # instantiation, operations.cc:283-414)
        self._compressors: Dict[int, object] = {}
        # per-key DEVICE codec adapters (core/device_codec.py): for bare
        # codec chains on jax inputs, COMPRESS runs on-device BEFORE the
        # D2H so the host boundary moves the compressed payload — the
        # inversion of the reference's CPU-post-staging compress
        # (core_loops.cc:498-536; SURVEY §7's genuine TPU improvement)
        self._device_codecs: Dict[int, object] = {}
        # adaptive compression (BYTEPS_COMPRESSION_AUTO): keys whose
        # observed wire ratio made the codec a loss — their later rounds
        # take the raw pipeline (the codec chain and the server-side
        # registration stay put: servers serve raw pushes/pulls on a
        # codec-registered key correctly, the mixed-config rule, so the
        # policy needs no wire coordination).  _auto_stats accumulates
        # (rounds, compressed bytes, raw bytes) per key until the verdict.
        self._compression_auto_off: set = set()
        self._auto_stats: Dict[int, list] = {}
        self._compression_lr: float = 1.0
        self._lr_sent_to_servers: float = 1.0
        # --- fleet tuning adoption (docs/autotune.md) ---
        # the scheduler's autotuner ships a versioned ``tuning`` section
        # in every book; the PS client replays it here.  Fleet codec
        # disables are tracked per codec name → the keys THIS engine
        # disabled for it, so a rollback re-enables exactly those.
        self._fuse_enabled = cfg.fusion_threshold > 0
        # the launch value: a tuning section WITHOUT a fusion_threshold
        # field means "untouched/legacy" — adoption restores this, so a
        # reborn scheduler's empty tuning state (or a rollback to the
        # pre-tuner value) actually lands fleet-wide
        self._launch_fusion_threshold = cfg.fusion_threshold
        self._codec_names: Dict[int, str] = {}
        self._fleet_codec_off: Dict[str, set] = {}
        # third tuner arm (docs/gradient-compression.md "Lossless frame
        # compression"): keys whose lossy codec lost the auto verdict
        # push raw — the entropy probe in _push_once checks whether the
        # raw bytes are compressible losslessly and, if so, stamps the
        # key's later pushes with the wire-level lossless container.
        # Python wire only (the native client's send path never frames).
        self._lossless_keys: set = set()
        self._lossless_probed: set = set()
        self._fleet_codec_lossless: Dict[str, set] = {}
        self._tuning_lock = threading.Lock()
        # the fleet fusion-threshold gauge feeds the tuner's walk (the
        # scheduler reads the aggregate's max as the fleet value)
        from byteps_tpu.core.telemetry import metrics as _metrics

        _metrics().gauge_set("fusion_threshold_bytes", cfg.fusion_threshold)
        add_listener = getattr(ps_client, "add_tuning_listener", None)
        if add_listener is not None:
            add_listener(self._apply_tuning)
        # tensor names whose last job failed degraded: their next submit
        # re-runs the init-push barrier, which resets the key's round
        # numbering on the (possibly healed) owners — without this the
        # abandoned round leaves client and server version counters
        # skewed and every later pull of that key pends forever
        self._reinit_names: set = set()

    # --- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Spawn one loop thread per host stage (BytePSGlobal::Start,
        global.cc:299-317).  The COMPRESS/DECOMPRESS striped pools spawn
        lazily when the first codec registers — uncompressed workers don't
        pay for 2×threadpool_size idle pollers."""
        stages = [
            (QueueType.COPYD2H, self._copy_d2h_once),
            (QueueType.PUSH, self._push_once),
            (QueueType.PULL, self._pull_once),
            (QueueType.COPYH2D, self._copy_h2d_once),
        ]
        if self.cfg.fusion_threshold > 0:
            # fusion off (the default) spawns no FUSE poller — the stage
            # only exists when small partitions can actually route to it
            stages.insert(1, (QueueType.FUSE, self._fuse_once))
        for qt, fn in stages:
            self._spawn_stage(qt, fn)

    def _spawn_stage(self, qt: QueueType, fn) -> None:
        q = self.queues[qt]
        stripes = q.stripes if isinstance(q, _StripedStage) else [q]
        for si, sq in enumerate(stripes):
            t = threading.Thread(
                target=self._loop, args=(sq, fn),
                name=f"bps-{qt.name}-{si}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _ensure_compress_threads(self) -> None:
        """First codec registration → bring up the striped pools."""
        if getattr(self, "_compress_started", False):
            return
        self._compress_started = True
        self._spawn_stage(QueueType.COMPRESS, self._compress_once)
        self._spawn_stage(QueueType.DECOMPRESS, self._decompress_once)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []

    def _loop(self, q: ScheduledQueue, fn) -> None:
        while not self._stop.is_set():
            task = q.get_task(timeout=0.2)
            if task is None:
                continue
            try:
                fn(task)
            except Exception as e:  # surface errors on the handle
                self._fail_task(
                    task, q.queue_type, repr(e),
                    degraded=isinstance(e, (ConnectionError, OSError)),
                )

    # --- submission ------------------------------------------------------

    def submit(
        self,
        name: str,
        tensor: Any,
        average: bool,
        priority: int,
        version: int,
        handle: int,
    ) -> None:
        """EnqueueTensor equivalent (operations.cc:182-281): lazily init the
        tensor (key range + server-side allocation barrier), partition, and
        drop every partition into the first stage queue.

        ``tensor`` may be a live jax Array: it is NOT materialized here —
        shape/dtype metadata is enough to partition, and the actual
        device→host transfer happens per partition on the COPYD2H stage
        thread (the reference's async COPYD2H stream, core_loops.cc:378-443),
        so the caller returns while the device is still computing.
        """
        import jax

        registry = get_registry()
        ctx = registry.declare(name)
        is_jax = isinstance(tensor, jax.Array)
        if is_jax:
            flat = tensor.reshape(-1)  # device-side metadata op, async
            np_dtype = np.dtype(flat.dtype)
        else:
            flat = np.ascontiguousarray(np.asarray(tensor)).reshape(-1)
            np_dtype = flat.dtype
        dtype_id = int(to_datatype(np_dtype))

        def build_partitions(c):
            partition_tensor(c, flat.size, np_dtype.itemsize, self.cfg.partition_bytes)

        def on_first_init():
            self._maybe_setup_compression(ctx, np_dtype, flat.size * np_dtype.itemsize)

        self._prepare_round(ctx, dtype_id, flat.size, build_partitions, on_first_init)
        # server-opt tensors pull UPDATED PARAMETERS, not gradient sums:
        # the worker-side divide must not fire (the declared rule folds
        # averaging server-side, same float op order)
        if self._server_opt_profile(ctx)[0]:
            average = False
        # jax input + bare codec chain ⇒ the device path: compress before
        # D2H, decode after H2D, assemble the result on device — no host
        # result buffer is ever written, so don't allocate one (the whole
        # point is that the gradient never exists uncompressed on host)
        on_device = (
            is_jax
            and bool(ctx.partitions)
            and all(p.key in self._device_codecs for p in ctx.partitions)
        )
        result = None if on_device else np.empty(flat.shape, dtype=np_dtype)
        job = _Job(
            name, ctx, flat, result, dtype_id, average, handle,
            pending=len(ctx.partitions), shape=np.shape(tensor),
            np_dtype=np_dtype, is_jax=is_jax, version=ctx.version,
            device_parts={} if on_device else None,
        )
        # small-tensor fusion routing, per partition: uncompressed
        # partitions gauge their RAW size against the threshold;
        # compressed partitions — host OR device codec — gauge their
        # WIRE size (codec wire_nbytes — the bytes that actually ride
        # the frame), so a 256KB tensor whose onebit payload is 8KB
        # fuses like any small tensor (docs/gradient-compression.md
        # "Compressed wire path" / "Device path").  A fused device
        # member rides the frame exactly like a host-compressed one
        # (COMPRESSED_PUSH_PULL cmd), and its reply slot feeds the
        # device decoder — the fused path never touches the host result
        # buffer device jobs deliberately don't allocate.
        fuse_limit = self.cfg.fusion_threshold
        itemsize = np_dtype.itemsize
        if self._traced():
            from byteps_tpu.core.tracing import new_trace_id

            job.trace_id = new_trace_id()
        self._step_begin()
        for part in ctx.partitions:
            p_compressed = (
                part.key in self._compressors
                and part.key not in self._compression_auto_off
            )
            if job.device_parts is not None:
                wire_est = self._device_codecs[part.key].wire_nbytes()
                small = bool(fuse_limit) and wire_est <= fuse_limit
                qlist = (
                    self.STAGES_DEVICE_COMPRESSED_FUSED if small
                    else self.STAGES_COMPRESSED
                )
            elif p_compressed:
                wire_est = self._compressors[part.key].wire_nbytes()
                small = bool(fuse_limit) and wire_est <= fuse_limit
                qlist = (
                    self.STAGES_COMPRESSED_FUSED if small
                    else self.STAGES_COMPRESSED
                )
            else:
                small = (
                    bool(fuse_limit)
                    and part.length * itemsize <= fuse_limit
                )
                qlist = self.STAGES_FUSED if small else self.STAGES
            if small:
                with self._fuse_lock:
                    self._staged_smalls += 1
            task = TensorTableEntry(
                tensor_name=name,
                key=part.key,
                priority=priority,
                version=ctx.version,
                offset=part.offset,
                length=part.length,
                total_partnum=len(ctx.partitions),
                queue_list=list(qlist),
                context=job,
                fuse_staged=bool(small),
                job=ctx.job,
            )
            self._stamp_task_trace(task, job)
            self.queues[QueueType.COPYD2H].add_task(task)

    def _prepare_round(self, ctx, dtype_id, n_elements, build_partitions,
                       on_first_init=None):
        """Shared per-submit bookkeeping for dense AND row-sparse paths:
        run (or, after an elastic server resize, RE-run) the init-push
        barrier, then advance the version and seed the PUSH round gate.

        - First init: build partitions, init every key (the blocking
          init-push doubles as the cross-worker barrier, operations.cc:
          283-414), then ``on_first_init`` (compressor setup).
        - server_generation mismatch (elastic resize): keys re-homed via
          the hash fns, so the init barrier re-runs against the new owners
          (their stores start fresh), compressor configs re-ship, and the
          version sequence restarts (the barrier reset server-side round
          counters) with the round gate re-seeded to match.  Under
          BYTEPS_ELASTIC_RESHARD this path never fires for a resize: the
          client does NOT bump server_generation when a book carries an
          ownership map (ps_client._rebuild_servers), because the servers
          migrate each re-homed key's state — store, exactly-once ledger,
          init-token record — to its new owner (docs/robustness.md
          "migration flow"), so the version sequence continues in place
          and pushes simply chase WRONG_OWNER redirects to the new home.
        - Gate seeding is per ENGINE, not per ctx-init: the registry (and
          its version counters) outlive shutdown()/init() cycles, while
          each engine starts with a fresh ReadyTable — a reused tensor name
          must start from its CURRENT version, not 1, or its tasks would
          never become eligible."""
        with self._init_lock:
            if ctx.partitions:
                declared = sum(p.length for p in ctx.partitions)
                if declared != n_elements:
                    # silent acceptance would scatter the new tensor into
                    # stores sized for the old one — garbage sums
                    raise ValueError(
                        f"tensor {ctx.name!r} re-used with a different size: "
                        f"declared {declared} elements, got {n_elements} "
                        "(name-keyed tensors keep a stable shape; use a "
                        "distinct name per tensor)"
                    )
            gen = getattr(self.client, "server_generation", 0)
            if (not ctx.initialized or ctx.server_generation != gen
                    or ctx.engine_epoch != self._epoch
                    or ctx.name in self._reinit_names):
                # engine_epoch mismatch: the registry survived a
                # shutdown()/init() cycle but this engine's servers are
                # new (fresh stores) — re-run the init barrier exactly
                # like a server resize, or the first push would hit an
                # uninitialized key and the server would drop the conn
                if not ctx.partitions:
                    build_partitions(ctx)
                if self._journal is not None:
                    # the barrier below restarts this key's round
                    # numbering: journaled payloads from the old
                    # numbering must never replay into the new one
                    for part in ctx.partitions:
                        self._journal.clear_key(part.key)
                is_async, staleness = self._async_profile(ctx)
                # the async kwargs ride only on async inits: sync keys
                # keep the classic call shape (and the classic 12-byte
                # wire payload), so stub clients and old transports
                # never see the extension
                akw = (
                    {"async_profile": True, "staleness": staleness}
                    if is_async else {}
                )
                opt_name, opt_hp = self._server_opt_profile(ctx)
                if opt_name:
                    # server-opt profile rides the same INIT extension
                    # (profile-byte bit 1 + rule block); "average" ships
                    # as a hyperparam because the divide now happens
                    # server-side, inside the rule
                    hp = dict(opt_hp)
                    hp.setdefault("average", True)
                    akw["server_opt"] = opt_name
                    akw["server_opt_hp"] = hp
                for part in ctx.partitions:
                    if self._traced():
                        from byteps_tpu.core.tracing import (
                            new_trace_id,
                            span_args,
                        )

                        t_id, s_id = new_trace_id(), new_trace_id()
                        t0 = time.time()
                        self.client.init_tensor(
                            part.key, part.length, dtype_id,
                            trace=(t_id, s_id), **akw,
                        )
                        self.tracer.record_span(
                            ctx.name, "INIT", t0, time.time() - t0,
                            span_args(t_id, s_id, key=part.key),
                        )
                    else:
                        self.client.init_tensor(
                            part.key, part.length, dtype_id, **akw,
                        )
                if ctx.initialized:
                    if (on_first_init is not None and not any(
                            p.key in self._compressors
                            for p in ctx.partitions)):
                        # registry-surviving tensor on a NEW engine (a
                        # shutdown()/init() cycle): this engine holds no
                        # codec chains for it, so re-run the compressor
                        # setup like a first init — reshipping an empty
                        # chain set would silently drop the tensor to
                        # raw for the rest of the process
                        on_first_init()
                    else:
                        self._reship_compressors(ctx)
                    ctx.version = 0
                    for part in ctx.partitions:
                        self._seeded.discard(part.key)
                elif on_first_init is not None:
                    on_first_init()
                ctx.initialized = True
                ctx.server_generation = gen
                ctx.engine_epoch = self._epoch
                self._reinit_names.discard(ctx.name)
            ctx.version += 1
            for part in ctx.partitions:
                if part.key not in self._seeded:
                    self._seeded.add(part.key)
                    self._push_ready.set_ready_count(part.key, ctx.version)

    def submit_rowsparse(
        self,
        name: str,
        indices: Any,
        values: Any,
        total_rows: int,
        average: bool,
        priority: int,
        version: int,
        handle: int,
    ) -> None:
        """Row-sparse push_pull (RequestType::kRowSparsePushPull,
        common.h:267-271): push (indices, values) rows of a
        ``(total_rows, row_len)`` tensor; the server scatter-sums into the
        dense store and the pull gathers the SAME indices back — the
        embedding-gradient path.  One key, no partitioning (the reference
        likewise exempts sparse tensors from byte partitioning)."""
        import struct

        idx, vals = validate_rowsparse(indices, values, total_rows)
        nrows, row_len = vals.shape
        dtype_id = int(to_datatype(vals.dtype))

        registry = get_registry()
        ctx = registry.declare(name)
        if self._server_opt_profile(ctx)[0]:
            # the row-sparse wire path scatter-sums rows into the dense
            # store; a server-side rule would update against a partial
            # accumulator — refuse instead of training wrong
            raise ValueError(
                f"tensor {name!r}: the server-side optimizer profile "
                "does not support row-sparse push_pull (dense only)"
            )

        def build_partitions(c):
            from byteps_tpu.common.types import Partition

            c.partitions = [
                Partition(
                    key=c.key_for_part(0), offset=0, length=total_rows * row_len
                )
            ]

        self._prepare_round(ctx, dtype_id, total_rows * row_len, build_partitions)
        key = ctx.partitions[0].key

        header = struct.pack("!II", nrows, row_len)
        idx_wire = idx.astype(">u4").tobytes()
        rowsparse = {
            "push_payload": header + idx_wire + vals.tobytes(),
            "pull_req": header + idx_wire,
        }
        result = np.empty(nrows * row_len, dtype=vals.dtype)
        job = _Job(
            name, ctx, None, result, dtype_id, average, handle,
            pending=1, shape=(nrows, row_len), np_dtype=vals.dtype,
            is_jax=False, version=ctx.version, rowsparse=rowsparse,
        )
        if self._traced():
            from byteps_tpu.core.tracing import new_trace_id

            job.trace_id = new_trace_id()
        self._step_begin()
        task = TensorTableEntry(
            tensor_name=name,
            key=key,
            priority=priority,
            version=ctx.version,
            offset=0,
            length=total_rows * row_len,
            total_partnum=1,
            queue_list=[QueueType.PUSH, QueueType.PULL],
            context=job,
            job=ctx.job,
        )
        self._stamp_task_trace(task, job)
        self.queues[QueueType.PUSH].add_task(task)

    def _maybe_setup_compression(self, ctx, np_dtype: np.dtype, nbytes: int) -> None:
        """Instantiate per-partition codec chains and ship the config to the
        owning servers (InitTensor's kCompressedPushPull push,
        operations.cc:396-408).  Engages only for fp32 tensors at least
        BYTEPS_MIN_COMPRESS_BYTES large (global.cc:137)."""
        from byteps_tpu.compression.registry import create_compressor

        has_cfg = any(
            k in ctx.kwargs
            for k in ("byteps_compressor_type", "compressor")
        )
        if not has_cfg or np_dtype != np.float32:
            return
        if nbytes < self.cfg.min_compress_bytes:
            return
        ctype = str(
            ctx.kwargs.get("byteps_compressor_type")
            or ctx.kwargs.get("compressor") or "?"
        )
        for part in ctx.partitions:
            codec = create_compressor(ctx.kwargs, part.length, server=False)
            if codec is None:
                return
            self._ensure_compress_threads()
            self._compressors[part.key] = codec
            # codec identity for the fleet consensus plane
            # (docs/autotune.md): the per-key local verdicts are labeled
            # with it, and a fleet codec_off decision matches keys by it
            self._codec_names[part.key] = ctype
            with self._tuning_lock:
                if ctype in self._fleet_codec_off:
                    # registered AFTER the fleet flipped this codec off:
                    # join the decision immediately
                    self._fleet_codec_off[ctype].add(part.key)
                    self._compression_auto_off.add(part.key)
            # a chain created after set_compression_lr must still honor it
            self._apply_lr_to_chain(codec, self._compression_lr)
            # BYTEPS_COMPRESSION_AUTO, static fast path: every shipped
            # codec's wire format is size-deterministic (wire_static →
            # wire_nbytes() is EXACT, not a bound), so the policy verdict
            # is computable at registration — no probe rounds, no
            # compressed bytes wasted discovering that k ≈ n.  The probe
            # path survives only for data-dependent codecs
            # (wire_static=False — custom chains whose payload size
            # varies with the gradient).
            if self.cfg.compression_auto and getattr(
                codec, "wire_static", False
            ):
                self._auto_static_verdict(part.key, codec)
            self.client.register_compressor(part.key, ctx.kwargs)
            from byteps_tpu.core.device_codec import device_codec_for

            dc = device_codec_for(ctx.kwargs, part.length)
            if dc is not None:
                self._device_codecs[part.key] = dc
        self._maybe_send_lr()

    def _reship_compressors(self, ctx) -> None:
        """After a server resize, re-register each partition's compressor
        config with the key's (possibly new) owning server; local chains —
        and their EF/momentum state — are kept."""
        shipped = False
        for part in ctx.partitions:
            if part.key in self._compressors:
                self.client.register_compressor(part.key, ctx.kwargs)
                shipped = True
        if shipped:
            # new server-side chains start at lr=1; resend the current lr
            self._lr_sent_to_servers = 1.0
            self._maybe_send_lr()

    @staticmethod
    def _apply_lr_to_chain(codec, lr: float) -> None:
        c = codec
        while c is not None:
            setter = getattr(c, "set_lr", None)
            if setter is not None:
                setter(lr)
            c = getattr(c, "inner", None)

    def set_compression_lr(self, lr: float) -> None:
        """Feed the current learning rate to every error-feedback stage —
        the worker-side chains here AND the server-side chains over the
        wire (replaces the reference's ``lr.s`` mmap,
        vanilla_error_feedback.h:44-58 — EF residual scaling tracks lr).

        Order-independent: an lr set before any compressor exists is
        remembered and applied when chains are created (worker side) /
        sent when the first chain registers (server side); repeat calls
        with an unchanged lr produce no wire traffic."""
        self._compression_lr = lr
        for codec in list(self._compressors.values()):
            self._apply_lr_to_chain(codec, lr)
        self._maybe_send_lr()

    def _maybe_send_lr(self) -> None:
        if self._compressors and self._compression_lr != self._lr_sent_to_servers:
            self.client.set_compression_lr(self._compression_lr)
            self._lr_sent_to_servers = self._compression_lr

    def _async_profile(self, ctx) -> tuple:
        """(async?, staleness bound) for a tensor's keys (docs/async.md):
        the declare-time ``byteps_async`` / ``byteps_staleness`` kwargs
        override the process-wide ``BYTEPS_ASYNC`` /
        ``BYTEPS_STALENESS_BOUND`` — per-key profiles on one worker."""
        raw = ctx.kwargs.get("byteps_async")
        if raw is None or raw == "":
            is_async = self.cfg.async_mode
        else:
            is_async = str(raw).lower() not in ("0", "false", "no", "off")
        if not is_async:
            return False, -1
        raw_s = ctx.kwargs.get("byteps_staleness")
        bound = (
            int(raw_s) if raw_s not in (None, "")
            else self.cfg.staleness_bound
        )
        return True, max(-1, bound)

    def _server_opt_profile(self, ctx) -> tuple:
        """(rule name or None, hyperparam dict) for a tensor's keys
        (docs/architecture.md "Server-side optimizer"): the declare-time
        ``byteps_server_opt`` / ``byteps_server_opt_hp`` kwargs override
        the process-wide ``BYTEPS_SERVER_OPT`` / ``BYTEPS_SERVER_OPT_HP``
        — per-tensor rules on one worker.  ``byteps_server_opt`` accepts
        a rule name, or a falsy string to force a tensor back to plain
        SUM under a fleet-wide rule."""
        raw = ctx.kwargs.get("byteps_server_opt")
        if raw is None or raw == "":
            name = self.cfg.server_opt
        elif str(raw).lower() in ("0", "false", "no", "off"):
            name = ""
        else:
            name = str(raw).strip().lower()
        if not name:
            return None, {}
        hp_raw = ctx.kwargs.get("byteps_server_opt_hp")
        if hp_raw in (None, ""):
            hp_raw = self.cfg.server_opt_hp
        if isinstance(hp_raw, dict):
            hp = dict(hp_raw)
        else:
            from byteps_tpu.server.update_rules import parse_hp

            hp = parse_hp(hp_raw)
        return name, hp

    @staticmethod
    def _job_labels(job: int):
        """``{"job": ...}`` for a tenant task, None for the default
        namespace — job 0 mints no extra label series, so single-tenant
        deployments see exactly the pre-tenancy families."""
        return {"job": str(job)} if job else None

    # --- observability helpers (docs/observability.md) -------------------

    def _step_begin(self) -> None:
        """One push_pull job entered the pipeline.  The first job after
        a quiescent stretch opens a new step window; the flight
        recorder stamps a ledger record when the count drains back to
        zero (round completion)."""
        with self._step_lock:
            if self._step_open == 0:
                self._step_t0 = time.monotonic()
            self._step_open += 1

    def _step_end(self, job: _Job) -> None:
        """A job left the pipeline (finalized OR failed) — exactly once
        per job.  Draining the in-flight count to zero completes the
        step: the flight recorder takes its registry delta and runs the
        trigger rules on it."""
        with job.lock:
            if job.step_counted:
                return
            job.step_counted = True
        with self._step_lock:
            if self._step_open <= 0:
                return
            self._step_open -= 1
            done = self._step_open == 0
            dur = time.monotonic() - self._step_t0
        if done:
            if self.cfg.job_id:
                # per-tenant step-time slice (docs/async.md): the
                # histogram feeds the cluster aggregate's per-job p99,
                # the gauge is the live value bps_top sparklines.  Job 0
                # (the single-tenant default) mints no extra series.
                from byteps_tpu.core.telemetry import metrics

                labels = {"job": str(self.cfg.job_id)}
                metrics().observe("job_step_seconds", dur, labels=labels)
                metrics().gauge_set(
                    "job_step_last_seconds", dur, labels=labels
                )
            if self._flight is not None and self._flight.enabled:
                self._flight.record_step(dur)

    def _traced(self) -> bool:
        return (
            self.tracer is not None
            and self.tracer.enabled
            and getattr(self.tracer, "spans_enabled", True)
        )

    def _stamp_task_trace(self, task: TensorTableEntry, job: _Job) -> None:
        """Give a partition task its span under the job's trace.  The
        span id is FIXED for the task's lifetime: every RPC attempt
        (retries included) carries the same id, so the server's
        dedupe-annotated child spans join the right worker span."""
        if job.trace_id:
            from byteps_tpu.core.tracing import new_trace_id

            task.trace_id = job.trace_id
            task.span_id = new_trace_id()

    def _task_trace(self, task: TensorTableEntry):
        """Wire trace context for a task's RPCs, or None when off."""
        return (task.trace_id, task.span_id) if task.trace_id else None

    # --- stage bodies ----------------------------------------------------

    def _proceed(self, task: TensorTableEntry) -> None:
        """FinishOrProceed (core_loops.cc:31-137): stamp the finished stage,
        advance to the next queue or finish the partition."""
        finished = task.queue_list.pop(0)
        job: _Job = task.context
        if self.cfg.debug_sample_tensor and self.cfg.debug_sample_tensor in job.name:
            # value sampling per stage (BYTEPS_DEBUG_SAMPLE_TENSOR,
            # core_loops.cc:37-67) — the race-diagnosis tool
            from byteps_tpu.common import logging as bpslog

            if job.device_parts is not None and finished in (
                QueueType.DECOMPRESS, QueueType.COPYH2D,
            ):
                # device-codec jobs never write job.result — the decoded
                # partition lives on device; sample it (device_get) rather
                # than the uninitialized host buffer
                part = job.device_parts.get(task.offset)
                buf = None if part is None else np.asarray(part)
            elif finished in (QueueType.DECOMPRESS, QueueType.COPYH2D) or (
                finished == QueueType.PULL and task.compressed is None
            ):
                # pull-side stages: sample what came BACK.  For compressed
                # tensors job.result is only written at DECOMPRESS, so the
                # PULL stage is skipped (payload is codec wire bytes).
                buf = job.result[task.offset : task.offset + task.length]
            elif finished == QueueType.PULL:
                buf = None
            else:
                buf = task.cpubuff
            if buf is not None and buf.size:
                bpslog.info(
                    "sample %s key=%d stage=%s v=%d norm=%.6g first=%.6g",
                    job.name, task.key, finished.name, task.version,
                    float(np.linalg.norm(buf.astype(np.float64))), float(buf[0]),
                )
        if self.tracer is not None:
            self.tracer.record(
                job.name, finished.name, job.t0, time.time() - job.t0, job.version
            )
        # per-stage dwell, ENQUEUE→done: the latency dimension the flat
        # counters never had — p99 here names the stalled stage directly
        if task.enqueued_at:
            from byteps_tpu.core.telemetry import metrics

            metrics().observe(
                "stage_dwell_seconds",
                time.monotonic() - task.enqueued_at,
                labels={"stage": finished.name},
            )
        if task.trace_id and self._traced():
            from byteps_tpu.core.tracing import span_args

            self.tracer.record_span(
                job.name, finished.name, task.enqueued_wall,
                time.time() - task.enqueued_wall,
                span_args(task.trace_id, task.span_id, key=task.key,
                          version=task.version),
            )
        self.queues[finished].report_finish(task)
        if task.queue_list:
            self.queues[task.queue_list[0]].add_task(task)
            return
        # partition fully round-tripped (push ACKed AND pull answered):
        # re-arm the key's PUSH gate so the next round may leave.  Re-arming
        # any earlier would let the server publish round N+1 before this
        # round's pull was served — the server hands pulls the LATEST
        # completed round (version <= store_version, server.cc:376-409)
        self._push_ready.add_ready_count(task.key)
        self.queues[QueueType.PUSH].notify()
        self.queues[QueueType.FUSE].notify()
        with job.lock:
            job.pending -= 1
            done = job.pending == 0
        if done:
            # close the step window BEFORE the handle completes: a
            # synchronous trainer resubmits the moment mark_done wakes
            # it, and a _step_begin racing in ahead of _step_end would
            # merge two rounds into one record (and skew the slow-step
            # rolling median)
            self._step_end(job)
            self._finalize(job)

    def _fail_job(self, job: _Job, status: Status) -> None:
        from byteps_tpu.core.state import get_state

        # step window closes before the handle completes — same
        # resubmission race as the _finalize path
        self._step_end(job)
        get_state().handles.mark_done(job.handle, None, status)

    def _fail_task(self, task: TensorTableEntry, stage: QueueType,
                   reason: str, degraded: bool = False) -> None:
        """Fail a task exactly once: return credits, advance the key's
        round allowance (a failed round can never advance it by completing),
        and surface the error on the handle — callers must never hang in
        synchronize() on a dead cluster.

        ``degraded`` (connection-class failures): the handle raises
        DegradedError — retryable — and the tensor is marked for a forced
        re-init barrier on its next submit.  The abandoned round skewed
        the key's version sequence between client and (possibly new)
        servers; the barrier resets both sides so a resubmitted step's
        pulls can actually complete instead of pending forever.

        Two paths can race here for one task — a stage-thread exception and
        the dead-connection error callback — so the job lock + task.failed
        guard makes the second a no-op (credits and the version allowance
        must not be double-counted)."""
        job = task.context
        if isinstance(job, _FusionGroup):
            # a GROUP task failing (stage-thread exception escaping
            # _push_group) has no job/handle of its own — return its
            # credit once and route the failure to its members, which own
            # all the real accounting.  Without this branch the generic
            # path would touch _Job-only fields and kill the PUSH stage
            # thread, stalling the whole pipeline.
            with job.lock:
                if job.done:
                    return
                job.done = True
            self.queues[QueueType.PUSH].report_finish(task)
            for mtask, _ in job.members:
                self._fail_task(mtask, QueueType.FUSE, reason, degraded=degraded)
            return
        with job.lock:
            if task.failed:
                return
            task.failed = True
            job.failed = True  # abort fence: stops sibling tasks' retries
        # a FUSE-routed task that died before reaching the fusion buffer
        # must leave the staging window, or the pinned counter disables
        # idle flushing forever
        self._unstage_small(task)
        self.queues[stage].report_finish(task)
        self._push_ready.add_ready_count(task.key)
        self.queues[QueueType.PUSH].notify()
        self.queues[QueueType.FUSE].notify()
        if degraded:
            from byteps_tpu.core.telemetry import counters

            counters().bump("degraded_jobs")
            self._reinit_names.add(job.name)
            self._fail_job(job, Status.Degraded(f"{stage.name}: {reason}"))
        else:
            self._fail_job(job, Status.Aborted(f"{stage.name}: {reason}"))

    def _finalize(self, job: _Job) -> None:
        """All partitions done: average (the plugin-side div by size,
        torch/ops.cc:78-91), reshape, hand back."""
        from byteps_tpu.core.state import get_state

        if job.device_parts is not None:
            # device-codec path: partitions were decoded ON device — the
            # assembly (concat/average/reshape) stays there too, so the
            # aggregated gradient never exists uncompressed on the host
            import jax.numpy as jnp

            parts = [job.device_parts[off] for off in sorted(job.device_parts)]
            out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            if job.average:
                out = out / self.client.num_workers
            get_state().handles.mark_done(job.handle, out.reshape(job.shape))
            return
        out = job.result
        if job.average and np.issubdtype(job.np_dtype, np.floating):
            out = out / self.client.num_workers
        out = out.reshape(job.shape)
        if job.is_jax:
            import jax

            # async H2D: device_put returns immediately with the transfer
            # in flight (the COPYH2D stream, core_loops.cc:650-753); the
            # caller's next jitted step consumes the Array when ready
            out = jax.device_put(out)
        get_state().handles.mark_done(job.handle, out)

    # --- recovery plane (docs/robustness.md "healing flow") --------------

    def heal_degraded(self, name: str, tensor: Any, average: bool):
        """In-place recovery for a tensor whose last job failed degraded
        while the cluster topology stayed put (one-sided degradation):
        resync every owning server — replaying the journaled pushes they
        never absorbed, which completes the abandoned round with the
        ORIGINAL payloads — then pull the published round and hand the
        caller the result it would have gotten fault-free.  Peers never
        block and no re-init barrier runs; on success the tensor's
        forced-re-init mark is cleared so its next submit continues the
        version sequence in place.

        Returns the aggregated (and averaged/reshaped) result, or None
        when in-place heal is not possible — topology changed under the
        job (the cluster-coherent re-init path owns that), compressed or
        device-codec keys (their pull needs the codec pipeline), resync
        refused (server restarted, journal gap, pre-parity binary), or the
        healed round's pull timed out.  The caller then falls back to
        the resubmit-with-re-init path, which is the pre-recovery
        behavior."""
        registry = get_registry()
        if not registry.is_declared(name):
            return None
        ctx = registry.get(name)
        gen = getattr(self.client, "server_generation", 0)
        with self._init_lock:
            if (name not in self._reinit_names or not ctx.initialized
                    or ctx.engine_epoch != self._epoch
                    or ctx.server_generation != gen or not ctx.partitions):
                return None
        if any(
            p.key in self._compressors or p.key in self._device_codecs
            for p in ctx.partitions
        ):
            return None
        import jax

        is_jax = isinstance(tensor, jax.Array)
        np_dtype = (
            np.dtype(tensor.dtype) if hasattr(tensor, "dtype")
            else np.asarray(tensor).dtype
        )
        shape = np.shape(tensor)
        total = sum(p.length for p in ctx.partitions)
        if int(np.prod(shape, dtype=np.int64)) != total:
            return None
        dtype_id = int(to_datatype(np_dtype))
        version = ctx.version
        # 1. resync each owning server: the replay of journaled pushes is
        # what completes the abandoned round server-side
        route_keys: Dict[int, int] = {}
        for p in ctx.partitions:
            try:
                route_keys.setdefault(self.client.server_for(p.key), p.key)
            except (ValueError, ZeroDivisionError, IndexError):
                return None
        for key in route_keys.values():
            if not self.client.resync_in_place(key):
                return None
        # 2. pull the (now completable) round into a fresh result buffer;
        # the pull's own retry/heal machinery applies per attempt
        result = np.empty(total, dtype=np_dtype)
        timeout = max(
            10.0,
            self.cfg.resync_deadline_s
            + (self.cfg.rpc_deadline_s or 1.0) * (self.cfg.rpc_retries + 1),
        )
        from byteps_tpu.comm.ps_client import _ZERO_COPIED

        # issue every partition's pull first, then wait: one round-trip
        # (and at worst one timeout) for the whole tensor, not P of them
        pending = []
        for p in ctx.partitions:
            done = threading.Event()
            box: dict = {}
            sink = memoryview(result).cast("B")[
                p.offset * np_dtype.itemsize
                : (p.offset + p.length) * np_dtype.itemsize
            ]

            def on_pull(payload, _box=box, _done=done):
                _box["payload"] = payload
                _done.set()

            self.client.pull(
                p.key, version, on_pull, dtype_id=dtype_id, sink=sink,
                on_error=lambda _done=done: _done.set(),
            )
            pending.append((p, done, box))
        deadline = time.monotonic() + timeout
        for p, done, box in pending:
            if not done.wait(max(0.0, deadline - time.monotonic())) or (
                "payload" not in box
            ):
                return None  # round still incomplete: fall back to re-init
            payload = box["payload"]
            if payload is not _ZERO_COPIED:
                arr = np.frombuffer(payload, dtype=np_dtype)
                result[p.offset : p.offset + p.length] = arr[: p.length]
        with self._init_lock:
            self._reinit_names.discard(name)
        out = result
        if average and np.issubdtype(np_dtype, np.floating):
            out = out / self.client.num_workers
        out = out.reshape(shape)
        if is_jax:
            out = jax.device_put(out)
        return out

    def _copy_d2h_once(self, task: TensorTableEntry) -> None:
        """Per-partition device→host DMA (COPYD2H, core_loops.cc:378-443).

        For jax inputs this is where the transfer actually happens — on
        THIS stage thread, one partition at a time, so the PUSH thread is
        already sending early partitions over DCN while later partitions
        are still coming off the device (and while the caller's next jitted
        step runs).  numpy inputs take a zero-copy slice view.

        Device-codec jobs invert the reference's order (compress AFTER
        staging, core_loops.cc:498-536): the Pallas/jnp packer runs on the
        DEVICE slice first, and what crosses the device→host boundary here
        is the compressed payload — 32× less for onebit."""
        from byteps_tpu.core.telemetry import counters

        job: _Job = task.context
        if job.device_parts is not None:
            dc = self._device_codecs[task.key]
            sl = job.flat[task.offset : task.offset + task.length]
            task.compressed = dc.compress(sl)  # D2H of the packed payload
            # the headline device-path number: bytes that actually
            # crossed the device→host boundary — compressed, vs the
            # host path's raw staging below (docs/observability.md;
            # tools/compression_bench.py D2H column)
            counters().bump("d2h_bytes", len(task.compressed))
            self._proceed(task)
            return
        sl = job.flat[task.offset : task.offset + task.length]
        task.cpubuff = sl if isinstance(sl, np.ndarray) else np.asarray(sl)
        if job.is_jax:
            counters().bump("d2h_bytes", task.cpubuff.nbytes)
        self._proceed(task)

    def _unstage_small(self, task: TensorTableEntry) -> None:
        """A FUSE-routed task left the staging window: it reached the
        fusion buffer (visible to the drain) or died upstream.  Exactly
        once per task — the idle-flush check (staged == 0 AND FUSE queue
        empty) must neither miss a small still in COPYD2H/COMPRESS nor
        stay pinned by one that failed there.  The test-and-clear runs
        under the fuse lock: _fuse_once and a racing _fail_task (a
        sibling's failure fanning out mid-stage) must not both
        decrement, or the counter goes negative and idle flush never
        fires again."""
        with self._fuse_lock:
            if task.fuse_staged:
                task.fuse_staged = False
                self._staged_smalls -= 1

    def _compress_once(self, task: TensorTableEntry) -> None:
        """COMPRESS stage (core_loops.cc:498-536): run the codec chain on
        the staged partition.  Stripe routing (key % pool size in
        _StripedStage) pins each key to one thread, so a key's stateful
        EF/momentum buffers never race across rounds while different keys
        compress in parallel."""
        if task.compressed is not None:
            # already packed on device in COPYD2H; stage is a pass-through
            # so traces keep the reference pipeline shape
            self._proceed(task)
            return
        codec = self._compressors[task.key]
        raw_nbytes = task.cpubuff.nbytes
        task.compressed = codec.compress(task.cpubuff)
        # wire-savings telemetry + the adaptive-compression policy feed
        # (docs/gradient-compression.md "Codec auto-selection")
        self._note_compression(task.key, raw_nbytes, len(task.compressed))
        self._proceed(task)

    def _apply_tuning(self, t: dict) -> None:
        """Adopt one fleet ``tuning`` section (docs/autotune.md) —
        invoked by the PS client on every newer-epoch book (and once at
        registration with the current section).  The fusion threshold
        is a single int store each submit() reads fresh, so adoption is
        atomic per round; codec flips move keys in/out of the
        auto-off set under the tuning lock."""
        from byteps_tpu.common import logging as bpslog
        from byteps_tpu.core.telemetry import counters, metrics

        ft = t.get("fusion_threshold")
        if ft is None:
            # field absent = "untouched": restore the launch value (a
            # reborn scheduler's fresh tuning state, or a tuner that
            # reverted to pre-tuner placement, must actually land)
            ft = self._launch_fusion_threshold
        if self._fuse_enabled:
            # never turns fusion ON from 0: the FUSE stage only exists
            # when the launch config enabled it (start() spawns no
            # poller otherwise) — the tuner's policy holds the same line
            try:
                ft = int(ft)
            except (TypeError, ValueError):
                ft = 0
            if ft > 0 and ft != self.cfg.fusion_threshold:
                bpslog.warning(
                    "autotune: fleet fusion threshold %d -> %d bytes",
                    self.cfg.fusion_threshold, ft,
                )
                self.cfg.fusion_threshold = ft
                metrics().gauge_set("fusion_threshold_bytes", ft)
        off = {str(n) for n in (t.get("codec_off") or ())}
        with self._tuning_lock:
            for name in sorted(off - set(self._fleet_codec_off)):
                keys = {
                    k for k, n in self._codec_names.items()
                    if n == name and k not in self._compression_auto_off
                }
                self._fleet_codec_off[name] = keys
                self._compression_auto_off.update(keys)
                if keys:
                    counters().bump(
                        "tune_codec_off", len(keys), labels={"codec": name}
                    )
                bpslog.warning(
                    "autotune: fleet codec consensus disabled %r "
                    "(%d local keys flip to raw)", name, len(keys),
                )
            for name in sorted(set(self._fleet_codec_off) - off):
                # rollback: re-enable exactly the keys the FLEET
                # decision disabled — locally-verdicted keys stay off
                keys = self._fleet_codec_off.pop(name)
                self._compression_auto_off.difference_update(keys)
                bpslog.warning(
                    "autotune: fleet codec decision on %r rolled back "
                    "(%d keys compress again)", name, len(keys),
                )
            # third arm (docs/gradient-compression.md "Lossless frame
            # compression"): adopt the fleet's codec_lossless names —
            # this engine's raw-pushing keys under a named codec start
            # shipping the wire lossless container.  Gated on the SAME
            # master switch as the probe: a worker with
            # BYTEPS_WIRE_LOSSLESS off ignores the names entirely so a
            # mixed-knob fleet never emits frames its peers can't want.
            from byteps_tpu.comm.transport import wire_lossless_enabled

            lz = {str(n) for n in (t.get("codec_lossless") or ())}
            if not wire_lossless_enabled():
                lz = set()
            for name in sorted(lz - set(self._fleet_codec_lossless)):
                keys = {
                    k for k, n in self._codec_names.items()
                    if n == name
                    and k in self._compression_auto_off
                    and k not in self._lossless_keys
                }
                self._fleet_codec_lossless[name] = keys
                self._lossless_keys.update(keys)
                if keys:
                    counters().bump(
                        "tune_codec_lossless", len(keys),
                        labels={"codec": name},
                    )
                bpslog.warning(
                    "autotune: fleet lossless arm on %r "
                    "(%d local raw keys ship the lossless frame)",
                    name, len(keys),
                )
            for name in sorted(set(self._fleet_codec_lossless) - lz):
                # rollback mirrors codec_off: exactly the fleet-marked
                # keys drop the transform; probe-verdicted keys keep it
                keys = self._fleet_codec_lossless.pop(name)
                self._lossless_keys.difference_update(keys)
                bpslog.warning(
                    "autotune: fleet lossless arm on %r rolled back "
                    "(%d keys push plain raw again)", name, len(keys),
                )

    def _auto_static_verdict(self, key: int, codec) -> None:
        """Registration-time verdict of the adaptive-compression policy
        for a size-deterministic codec: the exact wire ratio is
        ``wire_nbytes() / raw fp32 bytes``, so the key's fate is known
        before any round ships.  Either way the probe is marked complete
        (``_auto_stats[key] = None``) so ``_note_compression`` never
        accumulates probe state for it."""
        from byteps_tpu.core.telemetry import RATIO_BUCKETS, counters, metrics

        ratio = codec.wire_nbytes() / max(1, codec.size * 4)
        metrics().observe("compression_ratio", ratio, buckets=RATIO_BUCKETS)
        self._auto_stats[key] = None  # probe complete at registration
        if ratio < self.cfg.compression_auto_ratio:
            return
        self._compression_auto_off.add(key)
        # codec-labeled so the scheduler's codec_consensus policy can
        # count verdicts per codec per worker (docs/autotune.md); the
        # flat family keeps the pre-tuner totals
        counters().bump(
            "compression_auto_off",
            labels={"codec": self._codec_names.get(key, "?")},
        )
        from byteps_tpu.common import logging as bpslog

        bpslog.warning(
            "compression auto-disabled for key %d at registration: static "
            "wire ratio %.3f >= %.3f (BYTEPS_COMPRESSION_AUTO; codec wire "
            "size is deterministic, no probe rounds needed); rounds push "
            "raw", key, ratio, self.cfg.compression_auto_ratio,
        )

    def _lossless_probe(self, key: int, payload) -> None:
        """Third arm of the adaptive-compression policy (docs/gradient-
        compression.md "Lossless frame compression"): a key whose lossy
        codec lost the auto verdict pushes raw — probe ONE raw payload's
        byte entropy and, when it reads compressible (at or below
        BYTEPS_LOSSLESS_ENTROPY bits/byte), trial-run the wire lossless
        container.  A real win (>= 10% smaller) turns the transform on
        for this key's later pushes and casts the codec-labeled
        ``compression_auto_lossless`` vote the scheduler's
        codec_lossless quorum counts (docs/autotune.md).  One probe per
        key per engine; requires BYTEPS_WIRE_LOSSLESS so a fleet that
        keeps the wire feature off never sees a flagged frame."""
        self._lossless_probed.add(key)
        from byteps_tpu.comm.transport import wire_lossless_enabled

        if not wire_lossless_enabled():
            return
        from byteps_tpu.compression.lossless import (
            MIN_BYTES,
            byte_entropy,
            compress_frame,
            lossless_entropy_cutoff,
        )

        raw = bytes(payload[:65536])
        if len(raw) < MIN_BYTES:
            return
        ent = byte_entropy(raw)
        if ent > lossless_entropy_cutoff():
            return
        comp = compress_frame(raw)
        if len(comp) * 10 > len(raw) * 9:
            return  # entropy looked low but the LZ pass found no win
        with self._tuning_lock:
            self._lossless_keys.add(key)
        from byteps_tpu.core.telemetry import counters

        counters().bump(
            "compression_auto_lossless",
            labels={"codec": self._codec_names.get(key, "?")},
        )
        from byteps_tpu.common import logging as bpslog

        bpslog.warning(
            "lossless arm enabled for key %d: raw push entropy %.2f "
            "bits/byte, trial container %.2fx (BYTEPS_COMPRESSION_AUTO "
            "third arm); later pushes ship the wire lossless frame",
            key, ent, len(raw) / max(1, len(comp)),
        )

    def _note_compression(self, key: int, raw_nbytes: int,
                          comp_nbytes: int) -> None:
        """Record one compression's observed wire outcome and, with
        BYTEPS_COMPRESSION_AUTO on, run the per-key policy: after the
        probe rounds a key whose mean wire ratio (compressed/raw) is at
        or above the cutoff stops compressing — its later rounds take
        the raw pipeline (tiny tensors, k too close to n, codec overhead
        beating the savings).  Worker-local and per-key: the server
        serves raw traffic on a codec-registered key correctly (the
        mixed-config rule), so no wire coordination is needed.  Runs on
        the key's COMPRESS stripe thread, so per-key stats never race."""
        from byteps_tpu.core.telemetry import RATIO_BUCKETS, counters, metrics

        if comp_nbytes < raw_nbytes:
            counters().bump("wire_bytes_saved", raw_nbytes - comp_nbytes)
        # unlabeled on purpose: a per-key label would mint one histogram
        # series per compressed partition (unbounded cardinality — every
        # other label in the registry is bounded); the policy keeps its
        # per-key state in _auto_stats, and per-key wire sizes are
        # observable server-side via native_request_bytes{key}
        metrics().observe(
            "compression_ratio", comp_nbytes / max(1, raw_nbytes),
            buckets=RATIO_BUCKETS,
        )
        if not self.cfg.compression_auto or key in self._compression_auto_off:
            return
        st = self._auto_stats.get(key, False)
        if st is None:
            return  # probe complete, verdict was KEEP — stop tracking
        if st is False:
            st = self._auto_stats[key] = [0, 0, 0]
        st[0] += 1
        st[1] += comp_nbytes
        st[2] += raw_nbytes
        if st[0] < self.cfg.compression_auto_rounds:
            return
        ratio = st[1] / max(1, st[2])
        if ratio < self.cfg.compression_auto_ratio:
            self._auto_stats[key] = None  # keep the codec; one verdict
            return
        self._auto_stats.pop(key, None)
        from byteps_tpu.common import logging as bpslog

        # one verdict per key per engine (either way): the shipped
        # codecs' wire sizes are size-deterministic, so the observed
        # ratio cannot drift across the cutoff later
        self._compression_auto_off.add(key)
        counters().bump(
            "compression_auto_off",
            labels={"codec": self._codec_names.get(key, "?")},
        )
        bpslog.warning(
            "compression auto-disabled for key %d: observed wire "
            "ratio %.3f >= %.3f over %d rounds (BYTEPS_COMPRESSION_"
            "AUTO); later rounds push raw", key, ratio,
            self.cfg.compression_auto_ratio, st[0],
        )

    def _fuse_once(self, task: TensorTableEntry) -> None:
        """FUSE stage: stage a small partition into its destination
        server's fusion buffer instead of issuing a per-key push RPC.
        Compressed members (the COMPRESSED_FUSED pipeline) stage their
        codec wire bytes — what rides the member slot is exactly what an
        unfused compressed push would have sent.  Tasks leave the FUSE
        queue in priority order (and round-gated per key, same as PUSH),
        so packs fill highest-priority-first; the flushed group then
        re-enters the PUSH queue carrying the max member priority."""
        if task.compressed is not None:
            payload = task.compressed
        else:
            buf = task.cpubuff
            payload = (
                buf.data.cast("B") if buf.flags.c_contiguous
                else buf.tobytes()
            )
        self._fuser.add(task, payload)
        self._unstage_small(task)
        with self._fuse_lock:
            staging = self._staged_smalls
        if staging == 0 and self.queues[QueueType.FUSE].pending() == 0:
            # pipeline drained: every submitted small has reached the
            # buffer and none wait in the FUSE queue — this burst is over,
            # ship what we have rather than paying the cycle-timer latency
            # on every quiet round.  (Checking the FUSE queue alone is not
            # enough: the upstream stages feed us one task at a time and a
            # popped-but-unstaged task is invisible to pending() — that's
            # what the _staged_smalls counter tracks.)
            self._fuser.drain_idle()

    def _push_group(self, group_task: TensorTableEntry, group: _FusionGroup) -> None:
        """Ship one fusion pack as a single multi-key Op.FUSED RPC and fan
        the multi-key reply back out to the member tasks' PULL stages."""
        from byteps_tpu.core.telemetry import counters

        members = group.members

        def finish_group() -> bool:
            """Group bookkeeping exactly once (credit return); True for
            the winner of the deliver/on_error race."""
            with group.lock:
                if group.done:
                    return False
                group.done = True
            self.queues[QueueType.PUSH].report_finish(group_task)
            return True

        # the pack was grouped under the server mapping at FUSE time; an
        # elastic resize may have re-homed members since.  A frame whose
        # members no longer share a destination would ship keys to a
        # server that never initialized them — unfuse instead (per-key
        # pushes re-route per retry, surviving the resize like the
        # unfused path always has)
        sids = {self.client.server_for(mtask.key) for mtask, _ in members}
        if len(sids) > 1:
            if finish_group():
                self._unfuse_members(group, "server set resized under pack")
            return

        # per-member compressed flag: the member cmd Cantor-encodes the
        # request type, so a compressed member rides the SAME fused frame
        # as raw siblings with COMPRESSED_PUSH_PULL in its cmd — the
        # server routes it through the key's codec chain (decompress or
        # sparse-sum) and returns its reply slot codec-compressed.  Old
        # decoders already parse the cmd field, so no new wire bit is
        # needed (docs/gradient-compression.md "Compressed wire path").
        wire = [
            (
                mtask.key,
                get_command_type(
                    RequestType.COMPRESSED_PUSH_PULL
                    if mtask.compressed is not None
                    else RequestType.DEFAULT_PUSH_PULL,
                    mtask.context.dtype_id,
                ),
                mtask.version,
                payload,
            )
            for mtask, payload in members
        ]
        nbytes = sum(len(p) for _, _, _, p in wire)
        if self.telemetry is not None:
            self.telemetry.record(nbytes)
        counters().bump("fused_frames")
        counters().bump("fused_keys", len(members))
        counters().bump("wire_tx_bytes", nbytes,
                        labels=self._job_labels(group_task.job))
        if self._journal is not None:
            # each member journals individually: a resync replay re-sends
            # them as plain per-key pushes, which the server sums through
            # the same per-(worker, key) ledger a fused member uses
            for key, cmd, version, payload in wire:
                self._journal.record(key, version, cmd, payload, fused=True)

        # pack span: its own trace (members each belong to their jobs'
        # traces; their span ids ride the fused body's trailer so the
        # server can stamp per-member children) — fixed per frame, so a
        # RETRIED frame keeps the pack span and every member span
        pack_trace = None
        member_spans = None
        t_pack = time.time()
        if self._traced():
            from byteps_tpu.core.tracing import new_trace_id

            pack_trace = (new_trace_id(), new_trace_id())
            member_spans = [mtask.span_id for mtask, _ in members]

        def deliver(replies: list) -> None:
            if not finish_group():
                return
            if pack_trace is not None:
                from byteps_tpu.core.tracing import span_args

                self.tracer.record_span(
                    "<fused>", "FUSED_RPC", t_pack, time.time() - t_pack,
                    span_args(pack_trace[0], pack_trace[1],
                              keys=len(members)),
                )
            by_key = {key: payload for key, _ver, payload in replies}
            for mtask, _ in members:
                payload = by_key.get(mtask.key)
                if payload is None or mtask.context.failed:
                    self._fail_task(
                        mtask, QueueType.FUSE,
                        "fused reply missing member key"
                        if payload is None else "job aborted",
                        degraded=True,
                    )
                    continue
                mtask.fused_reply = payload
                self._proceed(mtask)  # FUSE done → PULL delivers locally

        def on_error() -> None:
            # fused retries exhausted (or the reply was malformed): fall
            # back to per-key unfused push+pull rather than failing the
            # members outright — per-key RPCs re-route on every retry, so
            # whatever broke the FRAME (resize mid-retry, a server that
            # can't serve fused traffic) doesn't have to cost the step.
            # A genuinely dead cluster still fails through the unfused
            # path's own retry budget, same as it always did.
            if not finish_group():
                return
            self._unfuse_members(group, "fused frame failed")

        self.client.push_fused(
            wire,
            cb=deliver,
            on_error=on_error,
            # the frame is abandoned only when EVERY member's job is —
            # one live member keeps the whole pack (and its siblings'
            # cleanup-by-delivery) in flight
            abort_check=lambda: all(m.context.failed for m, _ in members),
            trace=pack_trace,
            member_spans=member_spans,
        )

    def _unfuse_members(self, group: _FusionGroup, reason: str) -> None:
        """Fall back to per-key unfused push+pull for every live member of
        a pack that can't (or repeatedly didn't) ship as one frame.  The
        member re-enters the PUSH queue in place of its FUSE stage — its
        round allowance still holds (version gates are never consumed), so
        this is exactly the pipeline the partition would have taken with
        fusion off.  One-way: a fallback push that fails again surfaces
        through the normal per-task error path, no re-fusing loop."""
        from byteps_tpu.core.telemetry import counters

        counters().bump("fused_fallback")
        for mtask, _ in group.members:
            if mtask.context.failed or (
                not mtask.queue_list or mtask.queue_list[0] != QueueType.FUSE
            ):
                self._fail_task(
                    mtask, QueueType.FUSE, f"unfuse fallback: {reason}",
                    degraded=True,
                )
                continue
            mtask.queue_list[0] = QueueType.PUSH
            self.queues[QueueType.PUSH].add_task(mtask)

    def _push_once(self, task: TensorTableEntry) -> None:
        """Priority-ordered ZPush (RunPushLoopOnce, core_loops.cc:538-582)."""
        job = task.context
        if isinstance(job, _FusionGroup):
            self._push_group(task, job)
            return
        if job.rowsparse is not None:
            payload = job.rowsparse["push_payload"]
            rtype = RequestType.ROW_SPARSE_PUSH_PULL
        elif task.compressed is not None:
            payload = task.compressed
            rtype = RequestType.COMPRESSED_PUSH_PULL
        else:
            # zero-copy send: hand the staged partition's buffer straight
            # to the scatter-gather sendmsg (no tobytes() copy); fall back
            # to a copy only for non-contiguous staging buffers
            buf = task.cpubuff
            payload = (
                buf.data.cast("B")
                if buf.flags.c_contiguous
                else buf.tobytes()
            )
            rtype = RequestType.DEFAULT_PUSH_PULL
            if (
                self.cfg.compression_auto
                and task.key in self._compression_auto_off
                and task.key not in self._lossless_probed
            ):
                self._lossless_probe(task.key, payload)
        # third tuner arm: a raw-pushing key the entropy probe (or a
        # fleet codec_lossless decision) marked ships inside the wire
        # lossless container.  Compressed/rowsparse payloads never
        # qualify — the lossy codec already owns their bytes.
        lossless = (
            rtype == RequestType.DEFAULT_PUSH_PULL
            and task.key in self._lossless_keys
        ) or None
        if self.telemetry is not None:
            self.telemetry.record(len(payload))
        from byteps_tpu.core.telemetry import counters

        counters().bump("wire_tx_bytes", len(payload),
                        labels=self._job_labels(task.job))
        if self._journal is not None:
            # recovery plane: journal the exact wire payload BEFORE the
            # send, so a give-up on this very RPC can already replay it
            self._journal.record(
                task.key, task.version,
                get_command_type(rtype, job.dtype_id), payload,
            )
        self.client.push(
            task.key, payload, job.dtype_id, task.version,
            cb=lambda: self._proceed(task),
            request_type=rtype, lossless=lossless,
            on_error=lambda: self._fail_task(
                task, QueueType.PUSH, "server connection lost", degraded=True
            ),
            abort_check=lambda: job.failed,
            trace=self._task_trace(task),
        )

    def _pull_once(self, task: TensorTableEntry) -> None:
        """ZPull into the result buffer (RunPullLoopOnce,
        core_loops.cc:584-618)."""
        job: _Job = task.context
        # compressed-ness is a property of the TASK's pipeline, not of the
        # key: an auto-disabled key keeps its registered codec chain but
        # its later rounds ride the raw pipeline, and the pull must match
        # what this round's push actually sent
        compressed = (
            len(task.queue_list) > 1
            and task.queue_list[1] == QueueType.DECOMPRESS
        )
        if task.fused_reply is not None:
            # fused member: the multi-key reply already carried this key's
            # merged round — deliver locally, no wire pull.  Compressed
            # members' reply slots are codec-compressed (the server
            # compressed the merged round once); route them to DECOMPRESS
            # exactly like an unfused compressed pull's payload.
            payload = task.fused_reply
            task.fused_reply = None
            if self.telemetry is not None:
                self.telemetry.record(len(payload))
            from byteps_tpu.core.telemetry import counters

            counters().bump("wire_rx_bytes", len(payload),
                            labels=self._job_labels(task.job))
            if compressed:
                task.compressed = payload  # decoded by DECOMPRESS stage
            else:
                arr = np.frombuffer(payload, dtype=job.np_dtype)
                job.result[task.offset : task.offset + task.length] = (
                    arr[: task.length]
                )
            self._proceed(task)
            return

        if job.rowsparse is not None:
            def on_rs_pull(payload: bytes) -> None:
                from byteps_tpu.core.telemetry import counters

                if self.telemetry is not None:
                    self.telemetry.record(len(payload))
                counters().bump("wire_rx_bytes", len(payload),
                            labels=self._job_labels(task.job))
                arr = np.frombuffer(payload, dtype=job.np_dtype)
                job.result[: arr.size] = arr
                self._proceed(task)

            self.client.pull(
                task.key, task.version, on_rs_pull, dtype_id=job.dtype_id,
                request_type=RequestType.ROW_SPARSE_PUSH_PULL,
                payload=job.rowsparse["pull_req"],
                on_error=lambda: self._fail_task(
                    task, QueueType.PULL, "server connection lost",
                    degraded=True,
                ),
                abort_check=lambda: job.failed,
                trace=self._task_trace(task),
            )
            return

        # zero-copy receive target: the partition's byte range of the
        # result buffer — the aggregated payload lands there directly
        # (ZPull into the caller's SArray, core_loops.cc:584-618)
        sink = None
        if not compressed:
            sink = memoryview(job.result).cast("B")[
                task.offset * job.np_dtype.itemsize
                : (task.offset + task.length) * job.np_dtype.itemsize
            ]

        def on_pull(payload) -> None:
            from byteps_tpu.comm.ps_client import _ZERO_COPIED
            from byteps_tpu.core.telemetry import counters

            # actual WIRE bytes: a zero-copy sink is always the full
            # uncompressed partition; otherwise len(payload) is the
            # real (possibly compressed) transfer size
            nbytes = (
                task.length * job.np_dtype.itemsize
                if payload is _ZERO_COPIED
                else len(payload)
            )
            if self.telemetry is not None:
                self.telemetry.record(nbytes)
            counters().bump("wire_rx_bytes", nbytes,
                            labels=self._job_labels(task.job))
            if payload is _ZERO_COPIED:
                pass  # already in job.result via the sink
            elif compressed:
                task.compressed = payload  # decoded by DECOMPRESS stage
            else:
                # fallback (response length differed from the sink)
                arr = np.frombuffer(payload, dtype=job.np_dtype)
                job.result[task.offset : task.offset + task.length] = arr[: task.length]
            self._proceed(task)

        self.client.pull(
            task.key, task.version, on_pull, dtype_id=job.dtype_id,
            request_type=RequestType.COMPRESSED_PUSH_PULL
            if compressed else RequestType.DEFAULT_PUSH_PULL,
            sink=sink,
            on_error=lambda: self._fail_task(
                task, QueueType.PULL, "server connection lost", degraded=True
            ),
            abort_check=lambda: job.failed,
            trace=self._task_trace(task),
        )

    def _decompress_once(self, task: TensorTableEntry) -> None:
        """DECOMPRESS stage: decode the pulled merged payload
        (core_loops.cc:620-648).

        Device-codec jobs decode on DEVICE: the compressed payload is what
        crosses host→device (jnp.asarray inside the adapter), and the
        decoded partition stays on device for _finalize's assembly."""
        job: _Job = task.context
        if job.device_parts is not None:
            dc = self._device_codecs[task.key]
            part = dc.decompress(task.compressed, task.length)
            with job.lock:
                job.device_parts[task.offset] = part
            self._proceed(task)
            return
        codec = self._compressors[task.key]
        arr = codec.decompress(task.compressed, task.length)
        job.result[task.offset : task.offset + task.length] = arr[: task.length]
        self._proceed(task)

    def _copy_h2d_once(self, task: TensorTableEntry) -> None:
        """Host→device hand-back (COPYH2D, core_loops.cc:650-753).  The
        device transfer itself happens lazily in _finalize via jnp.asarray;
        this stage exists so tracing shows the full reference pipeline."""
        self._proceed(task)
