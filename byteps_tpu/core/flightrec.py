"""Flight recorder + anomaly trigger engine (docs/observability.md
"Flight recorder & doctor").

Every diagnosis surface this repo grew so far — wire spans, per-stage
dwell histograms, per-server labeled counters, the cluster aggregate —
is *pull*-shaped: an operator runs trace_merge or bps_top after the
incident.  At fleet scale the incident is over before anyone attaches a
profiler.  This module closes the loop:

- :class:`FlightRecorder` keeps an always-on bounded ring
  (``BYTEPS_FLIGHT_STEPS``, default 256; 0 disables) of per-step
  records stamped by the engine at round completion (and per heartbeat
  beat on servers).  Each record is ONE registry delta — step wall
  time, per-stage dwell deltas, per-server-rank RPC p99/retry/giveup
  deltas, wire tx/rx bytes, fused/compressed counts, robustness-event
  deltas, and the membership/map epoch + scheduler incarnation the step
  ran under.  No tracing required; the record costs a counter snapshot
  and a handful of bucket subtractions.
- A **trigger engine** evaluates a small rule table on every record:
  ``slow_step`` (rolling median × ``BYTEPS_FLIGHT_SLOW_FACTOR``),
  ``straggler_server`` (one rank's RPC p99 ≫ the median of its peers),
  ``hot_stripe`` (one native reducer's sum time ≫ its siblings, fed
  from ``native_stripe_sum_seconds{stripe}``), ``queue_stall`` (a
  stage's dwell p99 past ``BYTEPS_FLIGHT_STALL_S``),
  ``degraded_flip`` (``control_plane_degraded`` 0→1), and
  ``corruption_storm`` (a burst of ``wire_checksum_fail`` rejections or
  a connection dropped over its mismatch limit — docs/robustness.md
  "Wire integrity").  A firing rule
  bumps ``flight_trigger{rule}`` and dumps a rate-limited **diagnostic
  bundle** directory (``BYTEPS_FLIGHT_DIR``): the full ledger as
  JSONL, a metrics snapshot, config/env state, the trigger evidence,
  and a trace flush when tracing is on — everything
  ``tools/bps_doctor.py`` needs to rank a diagnosis offline.
- Each node piggybacks a compact **ledger tail** on its existing
  heartbeat (idempotent: the scheduler dedupes by step index), so the
  scheduler's :class:`ClusterFlight` holds a cluster-wide step matrix —
  who is the straggler *this* step, not last week's average — and
  exports it to ``tools/bps_top.py`` via the aggregate registry.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from byteps_tpu.core.telemetry import (
    _state_percentile,
    counters,
    metrics,
)

#: counter families copied (as nonzero deltas) into every record's
#: ``events`` map — the robustness story of the step, one dict read
EVENT_COUNTERS = (
    "resync_attempt", "resync_giveup", "resync_replayed_rounds",
    "worker_evicted", "server_evicted",
    "migration_keys_moved", "migration_keys_received", "migration_failed",
    "wrong_owner_redirect", "wrong_owner_served",
    "sched_reconnect", "sched_rejoin", "sched_stale_book",
    "degraded_jobs", "push_dedup", "rpc_deadline_expired", "rpc_retry",
    "rpc_giveup", "conn_revive",
    "chaos_drop", "chaos_delay", "chaos_disconnect", "chaos_truncate",
    "chaos_corrupt", "chaos_payload_corrupt",
    "wire_checksum_fail", "wire_checksum_conn_drop",
    "native_checksum_fail", "native_checksum_conn_drop",
)

#: histogram families whose per-label deltas feed the record (and the
#: trigger rules): (family name, label key, record field)
_HIST_FAMILIES = (
    ("stage_dwell_seconds", "stage", "stages"),
    ("rpc_round_trip_seconds", "server", "rpc"),
    ("native_stripe_sum_seconds", "stripe", "stripes"),
)

#: record keys kept in the compact heartbeat-tail form (plus "rpc" p99s)
_COMPACT_KEYS = ("step", "k", "t", "dur", "deg", "trig", "job")


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


class FlightRecorder:
    """Always-on per-step ring + node-side trigger rules.

    One instance per process (see :func:`ensure_process_recorder`);
    worker engines stamp a record at round completion
    (``record_step(dur)``), server control loops stamp one per
    heartbeat beat (``record_step()`` — rules that need a step duration
    skip).  All reads go through the process metrics registry, so
    in-process test fleets (worker + server sharing one registry)
    produce one coherent ledger.
    """

    def __init__(
        self,
        cfg=None,
        context_fn: Optional[Callable[[], dict]] = None,
        registry=None,
        counter_store=None,
        tracer=None,
        capacity: Optional[int] = None,
    ) -> None:
        self.capacity = (
            capacity if capacity is not None
            else getattr(cfg, "flight_steps", None)
            if cfg is not None and getattr(cfg, "flight_steps", None) is not None
            else _env_int("BYTEPS_FLIGHT_STEPS", 256)
        )
        self.slow_factor = (
            getattr(cfg, "flight_slow_factor", None)
            or _env_float("BYTEPS_FLIGHT_SLOW_FACTOR", 3.0)
        )
        self.stall_s = (
            getattr(cfg, "flight_stall_s", None)
            or _env_float("BYTEPS_FLIGHT_STALL_S", 5.0)
        )
        self.bundle_dir = (
            getattr(cfg, "flight_dir", None)
            or os.environ.get("BYTEPS_FLIGHT_DIR")
            or os.path.join(getattr(cfg, "trace_dir", ".") or ".",
                            "flight_bundles")
        )
        _fb = getattr(cfg, "flight_bundle_s", None) if cfg is not None else None
        self.bundle_interval_s = (
            float(_fb) if _fb is not None
            else _env_float("BYTEPS_FLIGHT_BUNDLE_S", 60.0)
        )
        #: fleet-central upload (BYTEPS_FLIGHT_UPLOAD, docs/
        #: observability.md): dumped trigger bundles additionally queue
        #: a COMPACT form (rule + evidence + firing record) that the
        #: heartbeat loop ships to the scheduler's BYTEPS_FLIGHT_DIR —
        #: tuner decisions and their trigger evidence land in one place
        self.upload = bool(
            getattr(cfg, "flight_upload", False)
            or os.environ.get("BYTEPS_FLIGHT_UPLOAD", "").lower()
            not in ("", "0", "false", "no", "off")
        )
        self._uploads: List[dict] = []
        #: per-job step-time SLO (docs/async.md): a completed step
        #: slower than this fires slo_breach (0 = rule off)
        self.slo_s = (
            getattr(cfg, "job_slo_s", None)
            if cfg is not None and getattr(cfg, "job_slo_s", None)
            else _env_float("BYTEPS_JOB_SLO_S", 0.0)
        )
        #: min prior samples before the rolling-median rules may fire
        self.min_history = 8
        self._context_fn = context_fn
        self._registry = registry if registry is not None else metrics()
        self._counters = (
            counter_store if counter_store is not None else counters()
        )
        self._tracer = tracer
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, self.capacity or 1))
        self._step = 0
        # delta baselines (one per source family; clamped at zero so a
        # test-style counters().reset() mid-flight can't go negative)
        self._base_counts: Dict[str, int] = {}
        self._base_labeled: Dict[str, Dict[tuple, int]] = {}
        self._base_hists: Dict[Tuple[str, tuple], Tuple[List[int], float, int]] = {}
        # rule state
        self._durs: deque = deque(maxlen=64)
        self._last_degraded: Optional[int] = None
        self._last_fire: Dict[str, float] = {}
        self.bundles_written: List[str] = []

    # --- properties ------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # --- recording -------------------------------------------------------

    def record_step(self, dur: Optional[float] = None) -> Optional[dict]:
        """Stamp one ledger record: the registry delta since the last
        record, plus the step wall time (worker rounds) and the control
        context.  Evaluates the trigger rules; returns the record (None
        when disabled).  Never raises into the data path."""
        if not self.enabled:
            return None
        try:
            return self._record_step(dur)
        except Exception as e:  # noqa: BLE001 — observability ≠ a crash
            from byteps_tpu.common import logging as bpslog

            bpslog.warning("flight recorder step failed: %r", e)
            return None

    def _record_step(self, dur: Optional[float]) -> dict:
        ctx = {}
        if self._context_fn is not None:
            try:
                ctx = self._context_fn() or {}
            except Exception:  # noqa: BLE001
                ctx = {}
        rec: dict = {
            "k": "step" if dur is not None else "beat",
            "t": time.time(),
            "dur": dur,
            "epoch": int(ctx.get("epoch", 0)),
            "map_epoch": int(ctx.get("map_epoch", 0)),
            "incarnation": int(ctx.get("incarnation", 0)),
            "deg": int(ctx.get("degraded", 0)),
            # multi-tenant dimension (docs/async.md): which job this
            # node's steps belong to (0 = single-tenant default) — the
            # per-tenant SLO rule and the cluster step matrix slice on it
            "job": int(ctx.get("job", 0)),
            "trig": [],
        }
        with self._lock:
            self._step += 1
            rec["step"] = self._step
            self._delta_counters(rec)
            self._delta_hists(rec)
            self._ring.append(rec)
        if dur is not None:
            self._registry.gauge_set("node_step_seconds", dur)
        self._evaluate(rec)
        if dur is not None:
            with self._lock:
                self._durs.append(dur)
        return rec

    def _delta_counters(self, rec: dict) -> None:
        """Nonzero counter deltas since the previous record.  Caller
        holds the lock."""
        flat = self._counters.snapshot()
        events = {}
        for name in EVENT_COUNTERS:
            d = flat.get(name, 0) - self._base_counts.get(name, 0)
            if d > 0:
                events[name] = d
        rec["events"] = events
        for name, field in (
            ("wire_tx_bytes", "tx"), ("wire_rx_bytes", "rx"),
            ("fused_frames", "fused"), ("fused_keys", "fused_keys"),
            ("wire_bytes_saved", "comp_saved"),
        ):
            rec[field] = max(0, flat.get(name, 0) - self._base_counts.get(name, 0))
        self._base_counts = flat
        # per-server retry/giveup slices ride into the rpc map below
        labeled = self._counters.snapshot_labeled()
        self._labeled_delta = {}
        for name in ("rpc_retry", "rpc_giveup"):
            per = labeled.get(name, {})
            base = self._base_labeled.get(name, {})
            d = {}
            for lkey, v in per.items():
                dd = v - base.get(lkey, 0)
                if dd > 0:
                    d[dict(lkey).get("server", "?")] = dd
            self._labeled_delta[name] = d
        self._base_labeled = {
            n: dict(per) for n, per in labeled.items()
            if n in ("rpc_retry", "rpc_giveup")
        }

    def _delta_hists(self, rec: dict) -> None:
        """Per-label bucket deltas for the watched histogram families →
        ``{label_value: {"n", "s", "p99"}}``.  Caller holds the lock."""
        states = self._registry._hist_states()
        wanted = {fam: (lab, field) for fam, lab, field in _HIST_FAMILIES}
        for fam, (lab, field) in wanted.items():
            rec[field] = {}
        for (name, lkey), st in states.items():
            if name not in wanted:
                continue
            lab, field = wanted[name]
            bounds, cnts, vsum, count = st
            base = self._base_hists.get((name, lkey))
            if base is None:
                d_counts, d_sum, d_count = list(cnts), vsum, count
            else:
                d_counts = [max(0, a - b) for a, b in zip(cnts, base[0])]
                d_sum = max(0.0, vsum - base[1])
                d_count = max(0, count - base[2])
            self._base_hists[(name, lkey)] = (list(cnts), vsum, count)
            if d_count <= 0:
                continue
            lv = dict(lkey).get(lab, "?")
            rec[field][lv] = {
                "n": d_count,
                "s": round(d_sum, 9),
                "p99": round(_state_percentile(tuple(bounds), d_counts, 0.99), 9),
            }
        # fold the labeled retry/giveup deltas into the rpc map so the
        # straggler evidence carries them
        for rank, v in (getattr(self, "_labeled_delta", {}) or {}).get(
            "rpc_retry", {}
        ).items():
            rec["rpc"].setdefault(rank, {"n": 0, "s": 0.0, "p99": 0.0})
            rec["rpc"][rank]["retry"] = v
        for rank, v in (getattr(self, "_labeled_delta", {}) or {}).get(
            "rpc_giveup", {}
        ).items():
            rec["rpc"].setdefault(rank, {"n": 0, "s": 0.0, "p99": 0.0})
            rec["rpc"][rank]["giveup"] = v

    # --- ledger access ---------------------------------------------------

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def ledger_tail(self, limit: int = 16) -> List[dict]:
        """The last ``limit`` records in compact wire form — the
        heartbeat piggyback.  Idempotent by design: every beat re-ships
        the window and the scheduler dedupes by step index, so a lost
        beat costs nothing."""
        with self._lock:
            recs = list(self._ring)[-max(1, limit):]
        out = []
        for r in recs:
            c = {k: r.get(k) for k in _COMPACT_KEYS}
            c["rpc"] = {
                rank: v.get("p99", 0.0) for rank, v in (r.get("rpc") or {}).items()
            }
            # per-stage dwell delta, compacted to {stage: [n, seconds]}:
            # the scheduler's fusion-threshold walk reads WHERE each
            # step's time went (docs/autotune.md "Fusion-threshold
            # walk"), not just how many packs crossed the wire
            st = {
                name: [v.get("n", 0), v.get("s", 0.0)]
                for name, v in (r.get("stages") or {}).items()
            }
            if st:
                c["st"] = st
            out.append(c)
        return out

    def take_uploads(self) -> List[dict]:
        """Drain the pending compact-bundle uploads (the heartbeat loop
        attaches them to the next beat as the ``fb`` field); a failed
        beat gives them back via :meth:`requeue_uploads`."""
        with self._lock:
            ups, self._uploads = self._uploads, []
            return ups

    def requeue_uploads(self, ups: List[dict]) -> None:
        with self._lock:
            self._uploads = (list(ups) + self._uploads)[-8:]

    # --- trigger engine --------------------------------------------------

    def _evaluate(self, rec: dict) -> None:
        for rule, fn in _RULES:
            try:
                ev = fn(self, rec)
            except Exception:  # noqa: BLE001 — a rule bug must not kill a step
                continue
            if ev is not None:
                self._fire(rule, ev, rec)

    def _fire(self, rule: str, evidence: dict, rec: dict) -> None:
        rec["trig"].append(rule)
        self._counters.bump("flight_trigger", labels={"rule": rule})
        now = time.monotonic()
        last = self._last_fire.get(rule)
        if last is not None and now - last < self.bundle_interval_s:
            return  # rate limiter holds: counted, not dumped
        self._last_fire[rule] = now
        try:
            path = self.dump_bundle(rule, evidence, rec)
        except Exception as e:  # noqa: BLE001
            from byteps_tpu.common import logging as bpslog

            bpslog.warning("flight bundle dump failed: %r", e)
            return
        self._counters.bump("flight_bundle")
        if self.upload:
            with self._lock:
                self._uploads.append({
                    "rule": rule,
                    "step": rec.get("step", 0),
                    "t": rec.get("t"),
                    "evidence": evidence,
                    "record": {k: rec.get(k) for k in _COMPACT_KEYS},
                    "bundle": os.path.basename(path),
                })
                # bounded: a heartbeat outage must not grow this forever
                del self._uploads[:-8]
        from byteps_tpu.common import logging as bpslog

        bpslog.warning(
            "flight trigger %s fired at step %d — diagnostic bundle: %s "
            "(inspect with: python tools/bps_doctor.py %s)",
            rule, rec["step"], path, path,
        )

    def dump_bundle(self, rule: str, evidence: dict, rec: dict) -> str:
        """Write one diagnostic bundle directory and return its path:
        ``trigger.json`` (rule + evidence + firing record),
        ``ledger.jsonl`` (the whole ring), ``metrics.json`` (full
        registry snapshot), ``config.json`` (BYTEPS_*/DMLC_* env +
        control context) — the exact input ``tools/bps_doctor.py``
        loads.  If tracing is on, the current trace window is flushed
        so the span view of the incident survives too."""
        ts = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(
            self.bundle_dir, f"{ts}-step{rec['step']}-{rule}-{os.getpid()}"
        )
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "trigger.json"), "w") as f:
            json.dump(
                {"rule": rule, "evidence": evidence, "record": rec,
                 "time": time.time(), "pid": os.getpid()},
                f, indent=2, default=str,
            )
        with open(os.path.join(path, "ledger.jsonl"), "w") as f:
            for r in self.snapshot():
                f.write(json.dumps(r, default=str) + "\n")
        with open(os.path.join(path, "metrics.json"), "w") as f:
            json.dump(self._registry.snapshot(), f, indent=2, default=str)
        env = {
            k: v for k, v in os.environ.items()
            if k.startswith(("BYTEPS_", "DMLC_"))
        }
        ctx = {}
        if self._context_fn is not None:
            try:
                ctx = self._context_fn() or {}
            except Exception:  # noqa: BLE001
                ctx = {}
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump({"env": env, "context": ctx}, f, indent=2, default=str)
        tracer = self._tracer
        if tracer is None:
            from byteps_tpu.core.tracing import get_process_tracer

            tracer = get_process_tracer()
        if tracer is not None and getattr(tracer, "enabled", False):
            try:
                trace_file = tracer.flush()
                with open(os.path.join(path, "trace_window.json"), "w") as f:
                    json.dump({"flushed_to": trace_file}, f)
            except Exception:  # noqa: BLE001
                pass
        self.bundles_written.append(path)
        return path


# --- the node-side rule table ---------------------------------------------
#
# Each rule: fn(recorder, record) → evidence dict (fire) or None.  Kept
# as plain functions so tests can drive them on synthetic records, and
# small on purpose: these run on every step of every node.


def _rule_slow_step(rec: "FlightRecorder", r: dict) -> Optional[dict]:
    """This step took ≫ the rolling median of recent steps."""
    dur = r.get("dur")
    if dur is None or len(rec._durs) < rec.min_history:
        return None
    med = statistics.median(rec._durs)
    if med > 0 and dur > med * rec.slow_factor:
        return {"dur": dur, "median": round(med, 6), "factor": rec.slow_factor}
    return None


def _rule_straggler_server(rec: "FlightRecorder", r: dict) -> Optional[dict]:
    """One server rank's RPC p99 this step ≫ the median of its peers."""
    cells = [
        (rank, v) for rank, v in (r.get("rpc") or {}).items()
        if rank != "?" and v.get("n", 0) > 0
    ]
    if len(cells) < 2:
        return None
    worst_rank, worst = max(cells, key=lambda kv: kv[1]["p99"])
    others = [v["p99"] for rank, v in cells if rank != worst_rank]
    med = statistics.median(others)
    # floor at the first latency bucket: loopback noise (p99s of tens
    # of µs) must never mint a straggler
    if worst["p99"] >= rec.slow_factor * max(med, 1e-4):
        return {
            "rank": worst_rank, "p99": worst["p99"],
            "peer_median_p99": round(med, 6),
            "retry": worst.get("retry", 0), "giveup": worst.get("giveup", 0),
        }
    return None


def _rule_hot_stripe(rec: "FlightRecorder", r: dict) -> Optional[dict]:
    """One native reducer stripe's summation time ≫ its siblings (fed
    from ``native_stripe_sum_seconds{stripe}`` deltas)."""
    cells = [
        (s, v) for s, v in (r.get("stripes") or {}).items()
        if v.get("n", 0) > 0
    ]
    if len(cells) < 2:
        return None
    worst_stripe, worst = max(cells, key=lambda kv: kv[1]["s"])
    others = [v["s"] for s, v in cells if s != worst_stripe]
    med = statistics.median(others)
    if worst["s"] >= rec.slow_factor * max(med, 1e-3):
        total = sum(v["s"] for _, v in cells)
        return {
            "stripe": worst_stripe, "sum_seconds": round(worst["s"], 6),
            "sibling_median": round(med, 6),
            "share": round(worst["s"] / max(total, 1e-12), 3),
        }
    return None


def _rule_queue_stall(rec: "FlightRecorder", r: dict) -> Optional[dict]:
    """A pipeline stage's dwell p99 this step exceeds the stall bound
    (``BYTEPS_FLIGHT_STALL_S``) — tasks are parking, not flowing."""
    hot = {
        st: v for st, v in (r.get("stages") or {}).items()
        if v.get("n", 0) > 0 and v["p99"] >= rec.stall_s
    }
    if not hot:
        return None
    worst = max(hot, key=lambda st: hot[st]["p99"])
    return {"stage": worst, "p99": hot[worst]["p99"], "stall_s": rec.stall_s}


def _rule_degraded_flip(rec: "FlightRecorder", r: dict) -> Optional[dict]:
    """``control_plane_degraded`` flipped 0→1: the scheduler link just
    died and the reconnect machine took over."""
    prev, rec._last_degraded = rec._last_degraded, r.get("deg", 0)
    if r.get("deg", 0) and not prev and prev is not None:
        return {"degraded": 1, "incarnation": r.get("incarnation", 0)}
    return None


def _rule_slo_breach(rec: "FlightRecorder", r: dict) -> Optional[dict]:
    """Per-tenant SLO (docs/async.md): a completed step blew the
    configured ``BYTEPS_JOB_SLO_S`` bound.  Unlike slow_step (relative
    to the rolling median — a uniformly slow job never fires it), this
    is the ABSOLUTE latency contract a tenant declared, so a bulk
    neighbor saturating the shared fleet shows up here first."""
    dur = r.get("dur")
    if dur is None or rec.slo_s <= 0 or dur <= rec.slo_s:
        return None
    return {
        "job": r.get("job", 0), "dur": dur, "slo_s": rec.slo_s,
        "over": round(dur / rec.slo_s, 3),
    }


#: checksum-mismatch deltas in ONE step/beat record at or above this
#: fire corruption_storm — a single flipped bit is the retry machinery's
#: job, a burst means the path itself is bad (NIC/DRAM going)
_CORRUPT_STORM_MIN = 3


def _rule_corruption_storm(rec: "FlightRecorder", r: dict) -> Optional[dict]:
    """Wire-integrity rejections are BURSTING (docs/robustness.md "Wire
    integrity"): many CRC32C mismatches landed inside one step/beat
    window, or a connection blew through its mismatch limit — a bad
    NIC/link is actively flipping bits, not a one-off cosmic ray."""
    ev = r.get("events") or {}
    # both engines: the C++ engine's rejections surface as native_* via
    # the counter-provider seam, same window, same record
    fails = (ev.get("wire_checksum_fail", 0)
             + ev.get("native_checksum_fail", 0))
    drops = (ev.get("wire_checksum_conn_drop", 0)
             + ev.get("native_checksum_conn_drop", 0))
    if fails < _CORRUPT_STORM_MIN and not drops:
        return None
    return {
        "checksum_fails": fails,
        "conn_drops": drops,
        "injected": ev.get("chaos_payload_corrupt", 0),
    }


_RULES: Tuple[Tuple[str, Callable], ...] = (
    ("slow_step", _rule_slow_step),
    ("straggler_server", _rule_straggler_server),
    ("hot_stripe", _rule_hot_stripe),
    ("queue_stall", _rule_queue_stall),
    ("degraded_flip", _rule_degraded_flip),
    ("slo_breach", _rule_slo_breach),
    ("corruption_storm", _rule_corruption_storm),
)


# --- scheduler-side cluster step matrix -----------------------------------


class ClusterFlight:
    """The scheduler's cluster-wide step matrix, fed by the compact
    ledger tails every node piggybacks on its heartbeat.  Dedupe is by
    per-node step index (tails are re-shipped windows).  Evaluates ONE
    scheduler-side rule — which worker is the straggler *this* step —
    and exports it to the aggregate scrape surface
    (``cluster_straggler_rank``; -1 = no straggler)."""

    def __init__(self, factor: Optional[float] = None,
                 depth: int = 64) -> None:
        self.factor = factor or _env_float("BYTEPS_FLIGHT_SLOW_FACTOR", 3.0)
        self._lock = threading.Lock()
        self._matrix: Dict[Tuple[str, int], deque] = {}
        self._last_step: Dict[Tuple[str, int], int] = {}
        self._depth = depth
        self.straggler_rank = -1
        self._registry = None

    def attach(self, registry) -> None:
        """Register the matrix's gauges on the scheduler's aggregate
        registry (idempotent)."""
        self._registry = registry
        registry.gauge_fn(
            "cluster_straggler_rank", lambda: float(self.straggler_rank)
        )

    def merge(self, role: str, rank: int, records: List[dict]) -> int:
        """Fold one node's heartbeat tail in; returns how many records
        were NEW (the rest were re-shipped window overlap)."""
        key = (role, int(rank))
        fresh = 0
        with self._lock:
            dq = self._matrix.setdefault(key, deque(maxlen=self._depth))
            last = self._last_step.get(key, 0)
            steps = []
            for r in records or ():
                try:
                    steps.append((int(r.get("step", 0)), r))
                except (TypeError, ValueError):
                    continue
            # restart detection: a LIVE node's tail always contains its
            # newest record, so a tail whose maximum step sits below the
            # dedupe cursor means the node's recorder restarted (process
            # restart / shutdown()+init() rejoin at the same rank).  The
            # dead incarnation's rows and cursor must not ghost-feed the
            # straggler rule or drop the reborn node's records forever.
            if steps and max(s for s, _ in steps) < last:
                dq.clear()
                last = 0
            for step, r in steps:
                if step <= last:
                    continue
                last = step
                dq.append(dict(r))
                fresh += 1
            self._last_step[key] = last
        if fresh:
            self._evaluate()
        return fresh

    def forget(self, role: str, rank: int) -> None:
        """Drop one node's row from the matrix — called when the
        scheduler evicts it, so a dead rank's frozen last-step duration
        stops feeding the straggler median."""
        key = (role, int(rank))
        with self._lock:
            self._matrix.pop(key, None)
            self._last_step.pop(key, None)
        self._evaluate()

    def _evaluate(self) -> None:
        """Scheduler-side straggler-node rule: the worker whose latest
        step wall time ≫ the median of its peers' latest steps."""
        with self._lock:
            durs = {}
            for (role, rank), dq in self._matrix.items():
                if role != "worker":
                    continue
                for r in reversed(dq):
                    if r.get("k") == "step" and r.get("dur") is not None:
                        durs[rank] = float(r["dur"])
                        break
        prev = self.straggler_rank
        if len(durs) < 2:
            self.straggler_rank = -1
            return
        worst_rank = max(durs, key=durs.get)
        others = [d for rk, d in durs.items() if rk != worst_rank]
        med = statistics.median(others)
        if durs[worst_rank] >= self.factor * max(med, 1e-4):
            self.straggler_rank = worst_rank
        else:
            self.straggler_rank = -1
        if self.straggler_rank >= 0 and self.straggler_rank != prev:
            if self._registry is not None:
                self._registry.counters.bump(
                    "flight_trigger", labels={"rule": "straggler_node"}
                )

    def matrix(self) -> Dict[str, List[dict]]:
        """``{"<role><rank>": [compact records, oldest first]}`` — the
        live surface ``bps_doctor --live`` and tests read."""
        with self._lock:
            return {
                f"{role}{rank}": list(dq)
                for (role, rank), dq in self._matrix.items()
            }


# --- process-global accessor ----------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_process_recorder() -> Optional[FlightRecorder]:
    return _recorder


def set_process_recorder(rec: Optional[FlightRecorder]) -> None:
    global _recorder
    with _recorder_lock:
        _recorder = rec


def release_process_recorder(context_fn) -> None:
    """Drop the process recorder iff ``context_fn`` is the one it was
    created with — how a stopping PSServer releases a recorder IT
    installed without clobbering one owned by a live worker runtime in
    the same process (the worker path releases via shutdown_state).  A
    stale recorder would leak a dead node's context — and its knob
    snapshot — into the next init cycle."""
    global _recorder
    with _recorder_lock:
        # == not `is`: each `self._flight_context` access builds a fresh
        # bound-method object; equality compares (__self__, __func__)
        if _recorder is not None and _recorder._context_fn == context_fn:
            _recorder = None


def ensure_process_recorder(cfg=None, context_fn=None,
                            tracer=None) -> FlightRecorder:
    """Create the process flight recorder if none exists yet (in-process
    test fleets: the first role to come up — worker state or a PSServer
    — owns it; later roles share the ring, which matches the shared
    metrics registry those fleets already run on)."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder(
                cfg=cfg, context_fn=context_fn, tracer=tracer
            )
        return _recorder
