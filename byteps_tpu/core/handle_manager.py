"""Async-handle table for push_pull_async / poll / synchronize.

Mirrors the reference torch plugin's HandleManager (handle_manager.h:32-43,
ops.py:51-236): monotonically increasing int handles, poll() checks
completion, synchronize() blocks and re-raises errors.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from byteps_tpu.common.types import DegradedError, Status, StatusType


class HandleManager:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._events: Dict[int, threading.Event] = {}
        self._results: Dict[int, Any] = {}
        self._status: Dict[int, Status] = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._events[h] = threading.Event()
            return h

    def mark_done(self, handle: int, result: Any, status: Optional[Status] = None) -> None:
        with self._lock:
            ev = self._events.get(handle)
            if ev is None:
                # late duplicate completion of an already-cleared handle
                # (e.g. a retried RPC resolving after its job failed and
                # the caller synchronized) — storing it would leak the
                # entry forever, since nobody will wait on it again
                return
            self._results[handle] = result
            self._status[handle] = status or Status.OK()
        ev.set()

    def poll(self, handle: int) -> bool:
        with self._lock:
            ev = self._events.get(handle)
        if ev is None:
            raise ValueError(f"unknown handle {handle}")
        return ev.is_set()

    def wait_and_clear(self, handle: int) -> Any:
        with self._lock:
            ev = self._events.get(handle)
        if ev is None:
            raise ValueError(f"unknown handle {handle}")
        ev.wait()
        with self._lock:
            result = self._results.pop(handle)
            status = self._status.pop(handle)
            del self._events[handle]
        if not status.ok():
            if status.type == StatusType.DEGRADED:
                # retryable: the data plane degraded under the op; the
                # caller (or BYTEPS_DEGRADED_STEP_RETRIES in api.py) may
                # resubmit the step once the cluster heals
                raise DegradedError(f"push_pull failed: {status.reason}")
            raise RuntimeError(f"push_pull failed: {status.reason}")
        return result

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._results.clear()
            self._status.clear()
