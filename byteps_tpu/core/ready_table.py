"""Key-count rendezvous table (ready_table.cc:24-44).

Used by the host engine as a precondition gate: a task for ``key`` may only
leave its queue when the expected number of ready signals has arrived (in
the reference: all local peers signalled REDUCE/PUSH/BCAST readiness over
UDS).  On TPU the intra-host peers are gone (one process drives all local
chips), but the table remains the rendezvous for cross-stage preconditions
(e.g. PULL must not start before PUSH acked) and for multi-controller
deployments.
"""

from __future__ import annotations

import threading
from typing import Dict


class ReadyTable:
    def __init__(self, ready_count: int, name: str = "") -> None:
        self.ready_count = ready_count
        self.name = name
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}

    def is_ready(self, key: int) -> bool:
        with self._lock:
            return self._counts.get(key, 0) >= self.ready_count

    def get_count(self, key: int) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def add_ready_count(self, key: int, n: int = 1) -> int:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n
            return self._counts[key]

    def set_ready_count(self, key: int, n: int) -> None:
        with self._lock:
            self._counts[key] = n

    def clear_ready_count(self, key: int) -> None:
        with self._lock:
            self._counts.pop(key, None)
