"""Priority-scheduled stage queue with per-tenant weighted fairness.

Re-design of ``BytePSScheduledQueue`` (scheduled_queue.cc):

- tasks sorted by (priority desc, key asc)  (scheduled_queue.cc:82-102)
- optional credit scheduling: a byte budget of in-flight work
  (BYTEPS_SCHEDULING_CREDIT, scheduled_queue.cc:26-46); finished tasks
  return their credits (reportFinish, scheduled_queue.cc:197-203)
- optional ReadyTable gate: tasks whose key is not ready are skipped
  (getTask, scheduled_queue.cc:125-163)

Priority semantics: the plugins assign priority = -declared_index so
gradients produced *last* in backprop (front layers) are communicated
*first*, hiding them behind the next step's early forward — the core BytePS
scheduling insight (OSDI'20 §4; mxnet/__init__.py:52-74).

Multi-tenant dimension (docs/async.md): tasks carry the JOB their key is
namespaced under (common/tenancy.py), and the queue runs weighted fair
queuing ACROSS jobs before the classic priority order applies WITHIN a
job.  Each job accumulates a virtual time — bytes served divided by its
weight (``BYTEPS_JOB_PRIORITY``; :func:`set_job_weight`) — and the pop
always serves the eligible job with the LOWEST virtual time:

- **starvation-freedom**: a weight-1 bulk job's virtual time eventually
  falls below a weight-100 latency job's (the latency job accumulates
  service too), so every tenant always progresses;
- **no priority inversion**: a bulk job's giant task.priority values
  cannot outrank another tenant — task priority only orders tasks of
  the SAME job, while the cross-job order is the weighted share.

With a single job in the queue (the default: one process = one tenant)
the virtual-time layer is inert and the order is bit-identical to the
classic (priority desc, key asc) scheduler.  Per-job gate credits
(``BYTEPS_JOB_CREDIT_BYTES``) bound each tenant's in-flight bytes the
way the global credit bounds the whole queue.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional

from byteps_tpu.common.types import QueueType, TensorTableEntry
from byteps_tpu.core.ready_table import ReadyTable

#: process-wide job → WFQ weight table (higher = larger share under
#: contention).  One process normally hosts one job and registers its
#: own BYTEPS_JOB_PRIORITY at engine start; in-process multi-tenant
#: fleets (tests, embedded runs) register every job they host.
_job_weights: Dict[int, float] = {}
_job_weights_lock = threading.Lock()


def set_job_weight(job: int, weight: float) -> None:
    """Register a tenant's weighted share (BYTEPS_JOB_PRIORITY)."""
    with _job_weights_lock:
        _job_weights[int(job)] = max(0.001, float(weight))


def get_job_weight(job: int) -> float:
    with _job_weights_lock:
        return _job_weights.get(int(job), 1.0)


class _JobLane:
    """One tenant's slice of a queue: its sorted task list plus the WFQ
    virtual-time account."""

    __slots__ = ("job", "tasks", "vtime", "inflight")

    def __init__(self, job: int) -> None:
        self.job = job
        self.tasks: List[TensorTableEntry] = []
        self.vtime = 0.0
        self.inflight = 0  # bytes this job currently has in flight


class ScheduledQueue:
    def __init__(
        self,
        queue_type: QueueType,
        credit_bytes: int = 0,
        ready_table: Optional[ReadyTable] = None,
        itemsize: int = 4,
        version_gated: bool = False,
        discipline: str = "priority",
        job_credits: Optional[Dict[int, int]] = None,
    ) -> None:
        if discipline not in ("priority", "fifo"):
            raise ValueError(
                f"BYTEPS_SCHEDULING={discipline!r} unknown; use priority|fifo"
            )
        #: "fifo" = strict arrival order — the ablation baseline proving the
        #: priority scheduler's wall-clock win (OVERLAP artifact); matches a
        #: reference build with scheduling disabled
        self.discipline = discipline
        self.queue_type = queue_type
        self.credit_enabled = credit_bytes > 0
        self._credits = credit_bytes
        #: per-tenant in-flight byte budgets (BYTEPS_JOB_CREDIT_BYTES);
        #: a job with no entry is bounded only by the global credit
        self._job_credits: Dict[int, int] = dict(job_credits or {})
        self._ready_table = ready_table
        # version-gated mode: a task is eligible iff its round number is at
        # or below the table's per-key allowance (counts[key] = highest
        # version allowed).  Enforces per-key ROUND ORDER, so a later
        # high-priority round can never overtake an earlier round of the
        # same key — priority still reorders across keys (the scheduling
        # win), never within one.
        self._version_gated = version_gated
        self._itemsize = itemsize
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        #: job → lane; insertion order is the FIFO tiebreak across jobs
        self._lanes: Dict[int, _JobLane] = {}

    def bind_ready_table(self, table: ReadyTable) -> None:
        self._ready_table = table

    def _lane_locked(self, job: int) -> _JobLane:
        lane = self._lanes.get(job)
        if lane is None:
            lane = self._lanes[job] = _JobLane(job)
        return lane

    def add_task(self, task: TensorTableEntry) -> None:
        # stage-entry stamps: the dwell histogram measures ENQUEUE→done
        # per stage, and span events start here — so queue wait (the
        # thing priority scheduling and credits actually change) is part
        # of every stage's recorded latency, not silently dropped
        task.enqueued_at = time.monotonic()
        task.enqueued_wall = time.time()
        with self._cv:
            lane = self._lane_locked(task.job)
            if not lane.tasks:
                # a (re-)activating tenant joins at the floor of the
                # live virtual clock: it must neither inherit a huge
                # service debt from its idle stretch (monopolizing the
                # queue) nor a huge credit (being starved while the
                # others catch up) — standard WFQ virtual-time join,
                # in NORMALIZED units (service / weight)
                active = [
                    ln.vtime / get_job_weight(ln.job)
                    for ln in self._lanes.values()
                    if ln.tasks and ln is not lane
                ]
                if active:
                    lane.vtime = max(
                        lane.vtime, min(active) * get_job_weight(lane.job)
                    )
            if self.discipline == "fifo":
                lane.tasks.append(task)
            else:
                # (priority desc, key asc) — scheduled_queue.cc:82-102;
                # bisect keeps insertion O(log n) compare + O(n) shift
                # instead of re-sorting the whole queue per task
                bisect.insort(
                    lane.tasks, task, key=lambda t: (-t.priority, t.key)
                )
            self._cv.notify_all()

    def _eligible(self, task: TensorTableEntry, lane: _JobLane) -> bool:
        nbytes = task.length * self._itemsize
        if self.credit_enabled and nbytes > self._credits:
            return False
        job_cap = self._job_credits.get(task.job)
        if job_cap is not None and lane.inflight + nbytes > job_cap:
            # this tenant's in-flight byte budget is spent — its tasks
            # wait for report_finish to return credits, while OTHER
            # tenants' tasks stay poppable (the whole point of the
            # per-job dimension)
            return False
        if task.gate_exempt:
            # fusion GROUP task: its members each passed their own per-key
            # round gate before being packed, and the pack's route key is
            # just the first member's — gating the group under that one key
            # would stall (or deadlock) the other members' rounds.  The
            # group still competes on priority (it inherits the max of its
            # members) and still spends credit, so fusion never defeats
            # priority scheduling or the in-flight byte budget.
            return True
        if self._ready_table is not None:
            if self._version_gated:
                if task.version > self._ready_table.get_count(task.key):
                    return False
            elif not self._ready_table.is_ready(task.key):
                return False
        return True

    def get_task(self, timeout: Optional[float] = None) -> Optional[TensorTableEntry]:
        """Pop the highest-priority eligible task of the least-served
        tenant; None on timeout.

        Re-waits the remaining budget after a wakeup that finds nothing
        eligible (spurious, or an ineligible add) — a single wait would
        hand the stage loop a None and cost a full idle poll tick."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                task = self._pop_eligible()
                if task is not None:
                    return task
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def _pop_eligible(self) -> Optional[TensorTableEntry]:
        # tenants in virtual-time order (ties broken by lane insertion
        # order — stable, so a single-job queue is exactly the classic
        # scheduler); within the chosen tenant, classic (priority desc,
        # key asc) order.  A tenant whose head tasks are all gated does
        # not block the others: the scan falls through to the next lane.
        lanes = sorted(
            (ln for ln in self._lanes.values() if ln.tasks),
            key=lambda ln: ln.vtime / get_job_weight(ln.job),
        )
        for lane in lanes:
            for i, t in enumerate(lane.tasks):
                if self._eligible(t, lane):
                    lane.tasks.pop(i)
                    nbytes = t.length * self._itemsize
                    if self.credit_enabled:
                        self._credits -= nbytes
                    if self._job_credits:
                        # tracked only when a tenant budget exists —
                        # report_finish's default fast path never
                        # decrements, so don't accumulate here either
                        lane.inflight += nbytes
                    # the service unit is BYTES (min 1 so zero-length
                    # control tasks still advance the clock): a tenant's
                    # share is of the wire, not of the pop count
                    lane.vtime += max(1, nbytes)
                    if (self._ready_table is not None
                            and not self._version_gated
                            and not t.gate_exempt):
                        # classic rendezvous consumes the accumulated
                        # signals (scheduled_queue.cc:125-163); the
                        # version gate keeps its allowance — completions
                        # advance it instead
                        self._ready_table.clear_ready_count(t.key)
                    return t
        return None

    def get_task_by_key(self, key: int) -> Optional[TensorTableEntry]:
        """Signal-directed dequeue (getTask(key),
        scheduled_queue.cc:165-190)."""
        with self._cv:
            for lane in self._lanes.values():
                for i, t in enumerate(lane.tasks):
                    if t.key == key:
                        return lane.tasks.pop(i)
        return None

    def report_finish(self, task: TensorTableEntry) -> None:
        """Return credits (scheduled_queue.cc:197-203) — global and the
        task's tenant budget.  No-op when neither credit dimension is
        armed (the default): the hot per-task completion path must not
        pay a lock + wakeup for bookkeeping nobody reads."""
        if not self.credit_enabled and not self._job_credits:
            return
        nbytes = task.length * self._itemsize
        with self._cv:
            if self.credit_enabled:
                self._credits += nbytes
            lane = self._lanes.get(task.job)
            if lane is not None:
                lane.inflight = max(0, lane.inflight - nbytes)
            self._cv.notify_all()

    def notify(self) -> None:
        """Wake waiters (ready-table state changed externally)."""
        with self._cv:
            self._cv.notify_all()

    def pending(self) -> int:
        with self._lock:
            return sum(len(ln.tasks) for ln in self._lanes.values())
