"""Priority-scheduled stage queue.

Re-design of ``BytePSScheduledQueue`` (scheduled_queue.cc):

- tasks sorted by (priority desc, key asc)  (scheduled_queue.cc:82-102)
- optional credit scheduling: a byte budget of in-flight work
  (BYTEPS_SCHEDULING_CREDIT, scheduled_queue.cc:26-46); finished tasks
  return their credits (reportFinish, scheduled_queue.cc:197-203)
- optional ReadyTable gate: tasks whose key is not ready are skipped
  (getTask, scheduled_queue.cc:125-163)

Priority semantics: the plugins assign priority = -declared_index so
gradients produced *last* in backprop (front layers) are communicated
*first*, hiding them behind the next step's early forward — the core BytePS
scheduling insight (OSDI'20 §4; mxnet/__init__.py:52-74).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from byteps_tpu.common.types import QueueType, TensorTableEntry
from byteps_tpu.core.ready_table import ReadyTable


class ScheduledQueue:
    def __init__(
        self,
        queue_type: QueueType,
        credit_bytes: int = 0,
        ready_table: Optional[ReadyTable] = None,
        itemsize: int = 4,
        version_gated: bool = False,
        discipline: str = "priority",
    ) -> None:
        if discipline not in ("priority", "fifo"):
            raise ValueError(
                f"BYTEPS_SCHEDULING={discipline!r} unknown; use priority|fifo"
            )
        #: "fifo" = strict arrival order — the ablation baseline proving the
        #: priority scheduler's wall-clock win (OVERLAP artifact); matches a
        #: reference build with scheduling disabled
        self.discipline = discipline
        self.queue_type = queue_type
        self.credit_enabled = credit_bytes > 0
        self._credits = credit_bytes
        self._ready_table = ready_table
        # version-gated mode: a task is eligible iff its round number is at
        # or below the table's per-key allowance (counts[key] = highest
        # version allowed).  Enforces per-key ROUND ORDER, so a later
        # high-priority round can never overtake an earlier round of the
        # same key — priority still reorders across keys (the scheduling
        # win), never within one.
        self._version_gated = version_gated
        self._itemsize = itemsize
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._tasks: List[TensorTableEntry] = []

    def bind_ready_table(self, table: ReadyTable) -> None:
        self._ready_table = table

    def add_task(self, task: TensorTableEntry) -> None:
        import bisect

        # stage-entry stamps: the dwell histogram measures ENQUEUE→done
        # per stage, and span events start here — so queue wait (the
        # thing priority scheduling and credits actually change) is part
        # of every stage's recorded latency, not silently dropped
        task.enqueued_at = time.monotonic()
        task.enqueued_wall = time.time()
        with self._cv:
            if self.discipline == "fifo":
                self._tasks.append(task)
            else:
                # (priority desc, key asc) — scheduled_queue.cc:82-102;
                # bisect keeps insertion O(log n) compare + O(n) shift
                # instead of re-sorting the whole queue per task
                bisect.insort(
                    self._tasks, task, key=lambda t: (-t.priority, t.key)
                )
            self._cv.notify_all()

    def _eligible(self, task: TensorTableEntry) -> bool:
        if self.credit_enabled and task.length * self._itemsize > self._credits:
            return False
        if task.gate_exempt:
            # fusion GROUP task: its members each passed their own per-key
            # round gate before being packed, and the pack's route key is
            # just the first member's — gating the group under that one key
            # would stall (or deadlock) the other members' rounds.  The
            # group still competes on priority (it inherits the max of its
            # members) and still spends credit, so fusion never defeats
            # priority scheduling or the in-flight byte budget.
            return True
        if self._ready_table is not None:
            if self._version_gated:
                if task.version > self._ready_table.get_count(task.key):
                    return False
            elif not self._ready_table.is_ready(task.key):
                return False
        return True

    def get_task(self, timeout: Optional[float] = None) -> Optional[TensorTableEntry]:
        """Pop the highest-priority eligible task; None on timeout.

        Re-waits the remaining budget after a wakeup that finds nothing
        eligible (spurious, or an ineligible add) — a single wait would
        hand the stage loop a None and cost a full idle poll tick."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                task = self._pop_eligible()
                if task is not None:
                    return task
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def _pop_eligible(self) -> Optional[TensorTableEntry]:
        for i, t in enumerate(self._tasks):
            if self._eligible(t):
                self._tasks.pop(i)
                if self.credit_enabled:
                    self._credits -= t.length * self._itemsize
                if (self._ready_table is not None and not self._version_gated
                        and not t.gate_exempt):
                    # classic rendezvous consumes the accumulated signals
                    # (scheduled_queue.cc:125-163); the version gate keeps
                    # its allowance — completions advance it instead
                    self._ready_table.clear_ready_count(t.key)
                return t
        return None

    def get_task_by_key(self, key: int) -> Optional[TensorTableEntry]:
        """Signal-directed dequeue (getTask(key),
        scheduled_queue.cc:165-190)."""
        with self._cv:
            for i, t in enumerate(self._tasks):
                if t.key == key:
                    return self._tasks.pop(i)
        return None

    def report_finish(self, task: TensorTableEntry) -> None:
        """Return credits (scheduled_queue.cc:197-203)."""
        if self.credit_enabled:
            with self._cv:
                self._credits += task.length * self._itemsize
                self._cv.notify_all()

    def notify(self) -> None:
        """Wake waiters (ready-table state changed externally)."""
        with self._cv:
            self._cv.notify_all()

    def pending(self) -> int:
        with self._lock:
            return len(self._tasks)
