"""Process-wide runtime state — the ``BytePSGlobal`` equivalent
(global.h:52-225, global.cc:105-403).

Owns: config snapshot, device mesh, tensor registry, handle table, the host
pipeline engine (distributed mode only), PS client, telemetry and tracer.
``init_state()`` is the body of ``byteps_lazy_init`` (operations.cc:41-88):
it selects which host loops exist based on role and distributed-ness.
"""

from __future__ import annotations

import threading
from typing import Optional

from byteps_tpu.common.config import Config, get_config, reset_config
from byteps_tpu.common.registry import TensorRegistry, get_registry
from byteps_tpu.core.handle_manager import HandleManager


class RuntimeState:
    def __init__(self) -> None:
        self.config: Optional[Config] = None
        self.mesh = None
        self.registry: Optional[TensorRegistry] = None
        self.handles = HandleManager()
        self.engine = None  # core.engine.PipelineEngine (distributed mode)
        self.ps_client = None  # comm.ps_client.PSClient
        self.flightrec = None  # core.flightrec.FlightRecorder
        self.telemetry = None  # core.telemetry.PushPullSpeed
        self.tracer = None  # core.tracing.Tracer
        self.metrics_http = None  # core.telemetry.MetricsHTTPServer
        self.initialized = False
        self.resuming = False
        # stable across suspend/resume so the scheduler matches the rejoin
        # to this worker's previous registration (not another live worker's);
        # resolved lazily at first init so a BYTEPS_NODE_UID set after import
        # still wins
        self.node_uid: Optional[str] = None
        self._lock = threading.Lock()


_state = RuntimeState()


def get_state() -> RuntimeState:
    return _state


_jax_distributed_up = False


def _init_jax_distributed(cfg: Config) -> None:
    """Bring up the JAX distributed runtime (multi-host pod slices;
    SURVEY §5.8: scheduler node ↔ jax.distributed coordinator).

    On Cloud TPU pods ``jax.distributed.initialize()`` auto-detects
    everything from instance metadata; elsewhere (multi-process CPU
    clusters, custom deployments) the coordinator must be explicit:

        BYTEPS_JAX_COORDINATOR=host:port
        BYTEPS_JAX_NUM_PROCESSES (default DMLC_NUM_WORKER)
        BYTEPS_JAX_PROCESS_ID    (default BYTEPS_GLOBAL_RANK/DMLC_WORKER_ID)

    The runtime survives suspend/resume (re-initializing the coordination
    service would drop every other host's connection; the reference's
    ps-lite similarly keeps its Postoffice across byteps_resume)."""
    global _jax_distributed_up
    if _jax_distributed_up:
        return
    import os

    import jax

    kwargs = {}
    coord = os.environ.get("BYTEPS_JAX_COORDINATOR", "")
    if coord:
        # empty-string env values (a common way to "unset" in env files)
        # fall back like missing ones
        pid = os.environ.get("BYTEPS_JAX_PROCESS_ID") or (
            cfg.global_rank if cfg.global_rank is not None else cfg.worker_id
        )
        nprocs = os.environ.get("BYTEPS_JAX_NUM_PROCESSES") or cfg.num_worker
        kwargs = dict(
            coordinator_address=coord,
            num_processes=int(nprocs),
            process_id=int(pid),
        )
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # tolerate a runtime someone else already brought up (jax's
        # message: "distributed.initialize should only be called once.")
        if "once" not in str(e).lower() and "already" not in str(e).lower():
            raise
    _jax_distributed_up = True


def init_state(fresh_env: bool = True) -> RuntimeState:
    """Bring the process up (global.cc:105-297 + operations.cc:41-88)."""
    import jax

    from byteps_tpu.comm.mesh import build_mesh, set_global_mesh
    from byteps_tpu.core.telemetry import PushPullSpeed
    from byteps_tpu.core.tracing import Tracer

    st = _state
    with st._lock:
        if st.initialized:
            return st
        # byteps_init re-reads env on every (re-)init — elastic resume
        # rewrites DMLC_* then re-initializes (operations.cc:96-112)
        cfg = reset_config() if fresh_env else get_config()
        st.config = cfg
        # log level tracks the env this runtime was started under, not
        # whichever import first loaded the logging module
        from byteps_tpu.common import logging as bpslog

        bpslog.apply_env_level()
        st.registry = get_registry()
        # multi-host JAX runtime (pod slices): opt-in coordinator bring-up —
        # the scheduler-node analogue for the ICI/DCN collective plane
        # (SURVEY §5.8: coordinator ↔ jax.distributed.initialize)
        import os

        if os.environ.get("BYTEPS_JAX_DISTRIBUTED", "0") == "1":
            _init_jax_distributed(cfg)
        st.mesh = build_mesh(cfg.mesh_shape)
        set_global_mesh(st.mesh)
        st.telemetry = PushPullSpeed(enabled=cfg.telemetry_on)
        st.tracer = Tracer(
            enabled=cfg.trace_on,
            start_step=cfg.trace_start_step,
            end_step=cfg.trace_end_step,
            trace_dir=cfg.trace_dir,
            local_rank=cfg.local_rank,
            spans_enabled=cfg.trace_spans,
        )
        # observability plane (docs/observability.md): chaos/ps layers
        # stamp events on the process tracer; the Prometheus endpoint
        # serves the process-global registry; push/pull MB/s rides along
        # as a lazy gauge so a scrape sees throughput next to latency
        from byteps_tpu.core.telemetry import metrics, serve_metrics
        from byteps_tpu.core.tracing import set_process_tracer

        set_process_tracer(st.tracer)
        metrics().gauge_fn("pushpull_mbps", st.telemetry.mbps)
        if cfg.metrics_port > 0 and st.metrics_http is None:
            st.metrics_http = serve_metrics(cfg.metrics_port)
        if cfg.is_distributed:
            # Distributed mode: bring up the PS client (rendezvous with the
            # scheduler, learn server addresses) and the staged host engine
            # (the loops the reference starts in BytePSGlobal::Start,
            # global.cc:299-403).
            from byteps_tpu.common.config import resolve_node_uid
            from byteps_tpu.comm.ps_client import PSClient
            from byteps_tpu.core.engine import PipelineEngine

            if st.node_uid is None:
                st.node_uid = resolve_node_uid()
            st.ps_client = PSClient(cfg, node_uid=st.node_uid)
            st.ps_client.connect()
            # cross-process span identity: the scheduler-assigned rank
            # names this process's track in merged timelines
            if st.ps_client.rank is not None:
                st.tracer.process_name = f"worker{st.ps_client.rank}"
            # flight recorder (docs/observability.md "Flight recorder &
            # doctor"): the engine stamps a ledger record per completed
            # round; the context closure lets each record carry the
            # membership/map epoch + scheduler incarnation it ran under
            from byteps_tpu.core.flightrec import ensure_process_recorder

            client = st.ps_client

            def _flight_ctx(c=client, job=cfg.job_id):
                return {
                    "epoch": c.membership_epoch,
                    "map_epoch": max(c.map_epoch, c._seen_map_epoch),
                    "incarnation": c.sched_incarnation,
                    "degraded": 0 if c._sched_up.is_set() else 1,
                    # multi-tenant dimension (docs/async.md): per-step
                    # records carry the job for the slo_breach rule and
                    # the cluster matrix's per-tenant slice
                    "job": job,
                }

            st.flightrec = ensure_process_recorder(
                cfg, context_fn=_flight_ctx, tracer=st.tracer
            )
            st.engine = PipelineEngine(
                cfg, st.ps_client, st.telemetry, st.tracer,
                flightrec=st.flightrec,
            )
            st.engine.start()
        st.initialized = True
        return st


def shutdown_state() -> None:
    """Tear down (byteps_shutdown → global.cc:319-403)."""
    st = _state
    with st._lock:
        if not st.initialized:
            return
        if st.engine is not None:
            st.engine.stop()
            st.engine = None
        if st.ps_client is not None:
            st.ps_client.close()
            st.ps_client = None
        if st.flightrec is not None:
            # drop the process recorder: its context closure holds the
            # closed client, and the next init owns a fresh ring
            from byteps_tpu.core.flightrec import (
                get_process_recorder,
                set_process_recorder,
            )

            if get_process_recorder() is st.flightrec:
                set_process_recorder(None)
            st.flightrec = None
        if st.tracer is not None:
            st.tracer.flush()
        if st.metrics_http is not None:
            st.metrics_http.close()
            st.metrics_http = None
        st.handles.clear()
        st.initialized = False


def require_state() -> RuntimeState:
    if not _state.initialized:
        raise RuntimeError("byteps_tpu not initialized; call byteps_tpu.init()")
    return _state
