"""Push/pull speed telemetry.

Re-design of ``BytePSGlobal::PushPullSpeed`` (global.cc:697-752): a windowed
MB/s counter over recent push_pull byte volume, exposed to Python as
``bps.get_pushpull_speed()`` (common/__init__.py:131-139).  Gate:
``BYTEPS_TELEMETRY_ON``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Tuple

WINDOW_SEC = 10.0  # reference uses a 10-second window (global.cc:703)


class PushPullSpeed:
    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: Deque[Tuple[float, int]] = deque()
        self._total_bytes = 0

    def record(self, nbytes: int) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            self._events.append((now, nbytes))
            self._total_bytes += nbytes
            self._evict(now)

    def _evict(self, now: float) -> None:
        while self._events and now - self._events[0][0] > WINDOW_SEC:
            _, nb = self._events.popleft()
            self._total_bytes -= nb

    def mbps(self) -> float:
        """Windowed MB/s (returns 0 when disabled or idle)."""
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            if not self._events:
                return 0.0
            span = max(now - self._events[0][0], 1e-6)
            return self._total_bytes / span / 1e6
