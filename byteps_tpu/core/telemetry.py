"""Metrics plane: push/pull speed telemetry, robustness counters, and the
cluster-scrapeable metrics registry (docs/observability.md).

Three layers, grown in place:

- :class:`PushPullSpeed` — the reference's ``BytePSGlobal::PushPullSpeed``
  (global.cc:697-752): a windowed MB/s counter over recent push_pull byte
  volume, exposed as ``bps.get_pushpull_speed()``.  Gate:
  ``BYTEPS_TELEMETRY_ON``.
- :class:`RobustnessCounters` (:func:`counters`) — named monotonic
  counters for data-plane degradation events, always on.  Since the
  observability PR they optionally carry a LABEL dimension (e.g.
  ``server="2"``) so a single sick peer is visible; flat totals are kept
  for back-compat (``get_robustness_counters``).
- :class:`MetricsRegistry` (:func:`metrics`) — counters + gauges +
  fixed-bucket histograms with p50/p90/p99 snapshots, a Prometheus text
  exposition endpoint (``BYTEPS_METRICS_PORT``), and delta snapshots that
  piggyback on the scheduler heartbeat so the scheduler can serve a
  cluster-wide aggregate.

Every metric name must appear in the docs/observability.md catalog —
``tools/check_metrics_doc.py`` (a tier-1 test) fails the build otherwise.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

WINDOW_SEC = 10.0  # reference uses a 10-second window (global.cc:703)


class PushPullSpeed:
    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: Deque[Tuple[float, int]] = deque()
        self._total_bytes = 0

    def record(self, nbytes: int) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            self._events.append((now, nbytes))
            self._total_bytes += nbytes
            self._evict(now)

    def _evict(self, now: float) -> None:
        while self._events and now - self._events[0][0] > WINDOW_SEC:
            _, nb = self._events.popleft()
            self._total_bytes -= nb

    def mbps(self) -> float:
        """Windowed MB/s (returns 0 when disabled or idle)."""
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            if not self._events:
                return 0.0
            span = max(now - self._events[0][0], 1e-6)
            return self._total_bytes / span / 1e6


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    """Canonical, hashable form of a label set."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class RobustnessCounters:
    """Named monotonic counters for data-plane degradation events.

    Canonical names (consumers may add others; the full catalog with
    per-name guidance lives in docs/observability.md):

    - ``rpc_retry``            — a push/pull/init attempt was re-sent
    - ``rpc_deadline_expired`` — a per-RPC deadline fired (hung server)
    - ``rpc_giveup``           — retries exhausted; error surfaced
    - ``conn_revive``          — a dead server connection was rebuilt
    - ``push_dedup``           — server suppressed a replayed push
    - ``degraded_jobs``        — engine jobs failed with DegradedError

    Recovery plane (docs/robustness.md "healing flow"; labeled per
    server rank like the rpc_* family):

    - ``resync_attempt``        — in-place heals started after a give-up
    - ``resync_replayed_rounds``— journaled push rounds replayed because
      the server's exactly-once ledger never absorbed them
    - ``resync_giveup``         — heals that failed; the caller fell
      back to the global re-init path
    - ``init_replay_ack``       — server acked a replayed INIT from its
      completed-barrier record (dropped-ack idempotency token)
    - ``worker_evicted`` / ``server_evicted`` — evictions observed from
      the scheduler's membership broadcasts (cumulative)
    - ``chaos_drop`` / ``chaos_delay`` / ``chaos_disconnect`` /
      ``chaos_truncate`` / ``chaos_corrupt`` — injected faults

    Small-tensor fusion (docs/perf.md):

    - ``wire_rpc``             — data-plane frames actually sent (every
      async push/pull/fused attempt, retries included) — the denominator
      ``tools/fusion_bench.py`` compares fused vs. unfused
    - ``fused_frames``         — multi-key Op.FUSED frames shipped
    - ``fused_keys``           — member partitions carried by those frames
      (``fused_keys / fused_frames`` = achieved pack density)
    - ``fusion_flush_full`` / ``fusion_flush_idle`` /
      ``fusion_flush_cycle`` — why each pack left the buffer (capacity
      reached / pipeline drained / BYTEPS_FUSION_CYCLE_MS backstop) —
      the first knob to read when tuning threshold vs. cycle
    - ``fused_fallback``       — packs downgraded to per-key unfused
      RPCs (server resize under the pack, or fused retries exhausted)
    - ``fused_reply_malformed`` — fused replies that failed to decode
      (routed to the frame's error path instead of the recv lane)

    ``bump(name, n, labels={"server": "2"})`` additionally records the
    count under that label set: ``rpc_retry``/``rpc_deadline_expired``/
    ``conn_revive`` carry a per-server-rank dimension so ONE sick server
    stands out of the flat total.  ``snapshot()`` stays flat ints
    (back-compat); :meth:`snapshot_labeled` exposes the dimension.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        # name → {label_key_tuple: count}; flat totals above INCLUDE these
        self._labeled: Dict[str, Dict[tuple, int]] = {}
        # External counter providers (docs/observability.md): zero-arg
        # callables returning {name: monotonic int}, merged into
        # snapshot()/get() — how the GIL-free C++ engine's counters
        # (native/__init__.py native_server_counters) reach the same
        # scrape surface without the data plane ever calling into
        # Python.  Each provider carries a baseline captured at reset()
        # so test-style reset semantics hold even though the native
        # counters themselves are never cleared.  Providers are invoked
        # UNDER self._lock (they are microsecond ctypes reads and must
        # not call back into this object — see register_provider), which
        # makes snapshot/reset/absorb mutually exclusive: a scrape can
        # never double-count a concurrently-absorbed provider.
        self._providers: Dict[int, tuple] = {}  # id → (fn, baseline)
        # totals folded in from absorbed (stopped) providers; cleared by
        # reset() like the flat counters
        self._frozen: Dict[str, int] = {}

    def bump(self, name: str, n: int = 1,
             labels: Optional[Dict[str, str]] = None,
             flat: bool = True) -> None:
        """``flat=False`` records only the labeled slice — used when the
        flat total is accounted separately (scheduler delta merge, where
        the unlabeled delta already includes the labeled bumps)."""
        with self._lock:
            if flat:
                self._counts[name] = self._counts.get(name, 0) + n
            if labels:
                key = _label_key(labels)
                per = self._labeled.setdefault(name, {})
                per[key] = per.get(key, 0) + n

    def set_floor(self, name: str, value: int) -> None:
        """Raise a counter to ``value`` if below it — used for cumulative
        totals observed from broadcasts, which may be re-delivered."""
        with self._lock:
            if self._counts.get(name, 0) < value:
                self._counts[name] = value

    def register_provider(self, fn) -> None:
        """Merge an external monotonic counter source (e.g. one native
        C++ server instance) into this store's snapshots.  ``fn`` must
        be fast (it runs under this store's lock — a microsecond ctypes
        read, not I/O), non-reentrant (it may not call back into
        counters()), and tolerate being called after its source stopped
        (return {})."""
        with self._lock:
            self._providers[id(fn)] = (fn, {})

    def unregister_provider(self, fn) -> None:
        with self._lock:
            self._providers.pop(id(fn), None)

    def absorb_provider(self, fn) -> None:
        """Fold a provider's final values (above its reset baseline)
        into the frozen-totals dict and unregister it — called before
        the provider's source is torn down so totals survive a server
        stop().  Runs entirely under the lock, so a concurrent scrape
        sees the totals through EITHER the live provider OR the frozen
        dict, never both (no double-count), and the registry does not
        grow with stopped servers."""
        with self._lock:
            entry = self._providers.pop(id(fn), None)
            if entry is None:
                return
            fn_live, base = entry
            try:
                vals = fn_live() or {}
            except Exception:  # noqa: BLE001
                vals = {}
            for name, v in vals.items():
                d = int(v) - base.get(name, 0)
                if d > 0:
                    self._frozen[name] = self._frozen.get(name, 0) + d

    def _provider_totals_locked(self) -> Dict[str, int]:
        """Frozen totals + every live provider's counters above its
        reset baseline.  Caller holds the lock (providers are contract-
        bound to be microsecond reads, see register_provider)."""
        total = dict(self._frozen)
        for fn, base in self._providers.values():
            try:
                vals = fn() or {}
            except Exception:  # noqa: BLE001 — a dead provider can't break scrape
                continue
            for name, v in vals.items():
                d = int(v) - base.get(name, 0)
                if d > 0:
                    total[name] = total.get(name, 0) + d
        return total

    def get(self, name: str) -> int:
        with self._lock:
            ext = (
                self._provider_totals_locked()
                if self._providers or self._frozen else {}
            )
            return self._counts.get(name, 0) + ext.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._counts)
            if self._providers or self._frozen:
                for name, v in self._provider_totals_locked().items():
                    out[name] = out.get(name, 0) + v
            return out

    def snapshot_labeled(self) -> Dict[str, Dict[tuple, int]]:
        """{name: {((label, value), ...): count}} for the labeled slice."""
        with self._lock:
            return {n: dict(per) for n, per in self._labeled.items()}

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._labeled.clear()
            self._frozen.clear()
            # re-baseline live providers so their post-reset deltas start
            # at zero (the native counters themselves are never cleared)
            for key, (fn, _base) in list(self._providers.items()):
                try:
                    self._providers[key] = (fn, dict(fn() or {}))
                except Exception:  # noqa: BLE001
                    self._providers[key] = (fn, {})


# Default latency buckets (seconds): 100µs → ~algo 100s, exponential —
# wide enough for a local UDS round trip and a cross-region DCN stall in
# the same histogram.
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 100.0,
)

#: pack-density buckets (member counts per fused frame)
COUNT_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)

#: request-size buckets (bytes) — MUST match native/hist.h kSizeBounds
#: (the native engine's per-key request-size histograms merge into the
#: same family, and bucket-merge needs identical bounds)
SIZE_BUCKETS: Tuple[float, ...] = (
    64, 256, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304, 16777216,
)

#: compression wire-ratio buckets (compressed bytes / raw bytes): dense
#: below 1.0 where the codecs live (onebit ~0.03, topk 2k/n, dithering
#: ~0.25), with >1 buckets so inflation — the adaptive policy's disable
#: signal — is visible in the same histogram
RATIO_BUCKETS: Tuple[float, ...] = (
    0.01, 0.02, 0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 0.9, 1.0, 1.5, 2.0,
)


class Histogram:
    """Fixed-bucket histogram with cheap percentile snapshots.

    Buckets are CUMULATIVE upper bounds (Prometheus ``le`` semantics)
    with an implicit +Inf bucket.  ``observe`` is one bisect + two adds
    under a lock — cheap enough to stay always-on in the data plane.
    Percentiles interpolate linearly inside the bucket that crosses the
    rank; observations past the last finite bound report that bound
    (the histogram's honest resolution limit).
    """

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_lock")

    def __init__(self, name: str,
                 buckets: Tuple[float, ...] = LATENCY_BUCKETS) -> None:
        self.name = name
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        import bisect

        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> dict:
        """{"count", "sum", "buckets": [(le, cumulative_count), ...]}
        with a trailing ("+Inf", count) entry."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        out, cum = [], 0
        for le, c in zip(self.bounds, counts):
            cum += c
            out.append((le, cum))
        out.append((float("inf"), total))
        return {"count": total, "sum": s, "buckets": out}

    def percentile(self, q: float) -> float:
        """q in [0, 1]; 0.0 on an empty histogram."""
        with self._lock:
            counts = list(self._counts)
        return _state_percentile(self.bounds, counts, q)

    def merge_counts(self, bucket_counts: List[int], vsum: float,
                     count: int) -> None:
        """Fold another histogram's RAW (non-cumulative) per-bucket counts
        in — the scheduler-side aggregation path.  Lengths must match."""
        with self._lock:
            for i, c in enumerate(bucket_counts[: len(self._counts)]):
                self._counts[i] += int(c)
            self._sum += vsum
            self._count += count

    def raw_counts(self) -> List[int]:
        with self._lock:
            return list(self._counts)

    def raw_state(self) -> Tuple[List[int], float, int]:
        """(non-cumulative bucket counts, sum, count) read under ONE lock
        acquisition — the delta path needs the three consistent with each
        other, or a racing observe() would ship a count with no bucket
        and skew the aggregate's percentiles until the next beat."""
        with self._lock:
            return list(self._counts), self._sum, self._count


def _state_percentile(bounds, counts, q: float) -> float:
    """Linear-interpolated percentile of a raw (bounds, per-bucket
    counts) state — the ONE interpolation both Histogram.percentile and
    the combined local+provider read path (MetricsRegistry._hist_states)
    use, operating directly on the state so a scrape never builds
    throwaway Histogram objects."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    prev_le, prev_cum, cum = 0.0, 0, 0
    for i, c in enumerate(counts):
        le = bounds[i] if i < len(bounds) else float("inf")
        cum += int(c)
        if cum >= rank and cum > prev_cum:
            if le == float("inf"):
                return bounds[-1] if bounds else prev_le
            span = cum - prev_cum
            frac = (rank - prev_cum) / span if span else 1.0
            return prev_le + (le - prev_le) * min(1.0, max(0.0, frac))
        prev_le, prev_cum = (0.0 if le == float("inf") else le), cum
    return bounds[-1] if bounds else 0.0


class MetricsRegistry:
    """Counters + gauges + histograms behind one scrape surface.

    Counters live in a :class:`RobustnessCounters` (so the pre-existing
    ``counters()`` surface IS the registry's counter store).  Histograms
    are keyed by (name, label set) — each label combination gets its own
    bucket array; exposition groups them under one metric family.
    Gauges are either set values or zero-argument callables sampled at
    render time.
    """

    def __init__(self, counter_store: Optional[RobustnessCounters] = None) -> None:
        self.counters = counter_store if counter_store is not None else RobustnessCounters()
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, tuple], Histogram] = {}
        # Histogram providers (docs/observability.md) — the twin of the
        # counter-provider seam in RobustnessCounters: zero-arg callables
        # returning raw-bucket records, merged into every read surface.
        # id → (fn, baseline captured at reset())
        self._hist_providers: Dict[int, tuple] = {}
        # gauges keyed by (name, label set), like histograms — label
        # combinations form one exposition family (the striped native
        # engine's native_stripe_queue_depth{stripe} is the first user)
        self._gauges: Dict[Tuple[str, tuple], float] = {}
        self._gauge_fns: Dict[Tuple[str, tuple], Callable[[], float]] = {}
        # delta baseline for heartbeat piggyback.  Normally one consumer
        # per process (the heartbeat loop), but in-process test clusters
        # run worker + server beats against one shared registry — the
        # lock keeps each increment shipped exactly once.
        self._delta_lock = threading.Lock()
        self._requeued: List[dict] = []  # failed-send deltas to re-ship
        self._shipped_counts: Dict[str, int] = {}
        self._shipped_labeled: Dict[str, Dict[tuple, int]] = {}
        self._shipped_hists: Dict[Tuple[str, tuple], Tuple[List[int], float, int]] = {}
        self._shipped_gauges: Dict[Tuple[str, tuple], float] = {}
        # the consumer token of the last reship_for() — a new scheduler
        # incarnation rebases the delta baselines exactly once even when
        # several beat loops share this registry (in-process fleets)
        self._reship_token = None

    # --- registration / recording ---------------------------------------

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  buckets: Tuple[float, ...] = LATENCY_BUCKETS) -> Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(name, buckets)
            return h

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None,
                buckets: Tuple[float, ...] = LATENCY_BUCKETS) -> None:
        self.histogram(name, labels, buckets).observe(value)

    def gauge_set(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._gauges[(name, _label_key(labels))] = float(value)

    def gauge_fn(self, name: str, fn: Callable[[], float],
                 labels: Optional[Dict[str, str]] = None) -> None:
        """Lazy gauge: ``fn()`` is sampled at exposition time."""
        with self._lock:
            self._gauge_fns[(name, _label_key(labels))] = fn

    def gauge_remove(self, name: str,
                     labels: Optional[Dict[str, str]] = None) -> None:
        """Drop one gauge series — how a stopping source (a native
        server's per-stripe depth feeds) leaves the scrape surface
        instead of exporting a dead callable forever."""
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges.pop(key, None)
            self._gauge_fns.pop(key, None)

    # --- histogram providers (native C++ engines) ------------------------

    def register_hist_provider(self, fn) -> None:
        """Merge an external histogram source into every read surface —
        the histogram twin of ``RobustnessCounters.register_provider``
        (docs/observability.md): how the GIL-free C++ engines' fixed-
        bucket histograms (the ``native_*`` families) reach
        ``get_metrics()``, the Prometheus exposition, and the heartbeat
        cluster aggregate without the data plane ever calling into
        Python.

        ``fn`` is a zero-arg callable returning an iterable of records
        ``{"name", "labels", "le", "b", "sum", "count"}`` where ``b``
        holds RAW (non-cumulative) per-bucket counts INCLUDING the +Inf
        slot (``len(b) == len(le) + 1``).  Bounds must match the Python
        family's buckets for the merge to compose.  ``fn`` must be
        cheap (a ctypes read + small JSON parse), and tolerate being
        called after its source stopped (return []).  A baseline is
        captured at :meth:`reset` so test-style reset semantics hold
        even though native histograms are never cleared."""
        with self._lock:
            self._hist_providers[id(fn)] = (fn, {})

    def unregister_hist_provider(self, fn) -> None:
        with self._lock:
            self._hist_providers.pop(id(fn), None)

    def absorb_hist_provider(self, fn) -> None:
        """Fold a provider's final values (above its reset baseline)
        into local histograms and unregister it — called before the
        provider's source is torn down (native server/client stop) so
        totals survive.  The combined totals are unchanged by the fold,
        so heartbeat deltas stay continuous across the absorb."""
        with self._lock:
            entry = self._hist_providers.pop(id(fn), None)
        if entry is None:
            return
        fn_live, base = entry
        try:
            recs = list(fn_live() or [])
        except Exception:  # noqa: BLE001 — a dead source has nothing to fold
            recs = []
        for key, st in self._hist_records_states(recs).items():
            name, lkey = key
            if not self._apply_baseline(st, base.get(key)):
                continue
            bounds, counts, vsum, count = st
            h = self.histogram(name, labels=dict(lkey) or None, buckets=bounds)
            if h.bounds == bounds:
                h.merge_counts(counts, vsum, count)

    @staticmethod
    def _apply_baseline(st, base) -> bool:
        """Subtract a :meth:`reset` baseline from a provider state
        ``[bounds, counts, sum, count]`` in place (clamped at zero);
        False when nothing remains above the baseline.  The ONE
        subtraction the absorb and scrape paths share, so their
        semantics can't diverge."""
        if base is not None:
            st[1] = [max(0, a - x) for a, x in zip(st[1], base[0])]
            st[2] = max(0.0, st[2] - base[1])
            st[3] = max(0, st[3] - base[2])
        return st[3] > 0

    @staticmethod
    def _hist_records_states(recs) -> Dict[Tuple[str, tuple], list]:
        """Provider records → {(name, label-key): [bounds, counts, sum,
        count]}, malformed records dropped, duplicate (name, labels)
        entries (several providers feeding one family) summed."""
        out: Dict[Tuple[str, tuple], list] = {}
        for rec in recs or ():
            try:
                name = str(rec["name"])
                lkey = _label_key(rec.get("labels") or None)
                bounds = tuple(float(b) for b in rec["le"])
                counts = [int(c) for c in rec["b"]]
                vsum = float(rec["sum"])
                count = int(rec["count"])
            except (KeyError, TypeError, ValueError):
                continue
            if len(counts) != len(bounds) + 1 or count < 0:
                continue
            cur = out.get((name, lkey))
            if cur is None:
                out[(name, lkey)] = [bounds, counts, vsum, count]
            elif cur[0] == bounds:
                cur[1] = [a + b for a, b in zip(cur[1], counts)]
                cur[2] += vsum
                cur[3] += count
        return out

    def _hist_states(self) -> Dict[Tuple[str, tuple], list]:
        """(name, label-key) → [bounds, raw_counts, sum, count] across
        local histograms AND live providers (above their reset
        baselines) — the ONE combined read path snapshot(), the
        Prometheus render, and the heartbeat delta all share, so every
        surface reports the same totals.  Providers are invoked OUTSIDE
        the registry lock (they parse JSON off a ctypes read)."""
        with self._lock:
            hists = dict(self._hists)
            providers = list(self._hist_providers.values())
        out: Dict[Tuple[str, tuple], list] = {}
        for (name, lkey), h in hists.items():
            counts, vsum, count = h.raw_state()
            out[(name, lkey)] = [h.bounds, counts, vsum, count]
        for fn, base in providers:
            try:
                recs = list(fn() or [])
            except Exception:  # noqa: BLE001 — a dead provider can't break scrape
                continue
            for key, st in self._hist_records_states(recs).items():
                if not self._apply_baseline(st, base.get(key)):
                    continue
                bounds, counts, vsum, count = st
                cur = out.get(key)
                if cur is None:
                    out[key] = [bounds, counts, vsum, count]
                elif tuple(cur[0]) == bounds:
                    cur[1] = [a + x for a, x in zip(cur[1], counts)]
                    cur[2] += vsum
                    cur[3] += count
        return out

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()
            self._gauges.clear()
            self._gauge_fns.clear()
            providers = list(self._hist_providers.items())
        # re-baseline live histogram providers so their post-reset
        # deltas start at zero (native histograms are never cleared).
        # fn() parses JSON off a ctypes read — call it OUTSIDE the
        # registry lock (same rule as _hist_states) so a slow native
        # read can't stall every observe/scrape in the process.
        rebased = []
        for key, (fn, _base) in providers:
            try:
                base = {
                    k: (st[1], st[2], st[3])
                    for k, st in self._hist_records_states(
                        list(fn() or [])
                    ).items()
                }
            except Exception:  # noqa: BLE001
                base = {}
            rebased.append((key, fn, base))
        with self._lock:
            for key, fn, base in rebased:
                # a provider absorbed/unregistered while unlocked must
                # not be resurrected
                if key in self._hist_providers:
                    self._hist_providers[key] = (fn, base)
        with self._delta_lock:
            self._requeued.clear()
            self._shipped_counts.clear()
            self._shipped_labeled.clear()
            self._shipped_hists.clear()
            self._shipped_gauges = {}
            self._reship_token = None
        self.counters.reset()

    # --- snapshots -------------------------------------------------------

    def snapshot(self) -> dict:
        """Full structured snapshot: counters (flat + labeled), gauges,
        histogram percentiles — the in-process observability surface
        (``bps.get_metrics()``).  Histograms are the COMBINED view:
        local observations plus live histogram providers (the native
        C++ engines' ``native_*`` families)."""
        with self._lock:
            gauges = dict(self._gauges)
            gauge_fns = dict(self._gauge_fns)
        out = {
            "counters": self.counters.snapshot(),
            "counters_labeled": {
                name: {_render_labels(k) or "{}": v for k, v in per.items()}
                for name, per in self.counters.snapshot_labeled().items()
            },
            "gauges": {
                name + _render_labels(lkey): v
                for (name, lkey), v in gauges.items()
            },
            "histograms": {},
        }
        for (name, lkey), fn in gauge_fns.items():
            try:
                out["gauges"][name + _render_labels(lkey)] = float(fn())
            except Exception:  # noqa: BLE001 — a broken gauge can't break scrape
                continue
        for (name, lkey), st in self._hist_states().items():
            bounds, counts, vsum, count = st
            out["histograms"][name + _render_labels(lkey)] = {
                "count": count,
                "sum": vsum,
                "p50": _state_percentile(bounds, counts, 0.50),
                "p90": _state_percentile(bounds, counts, 0.90),
                "p99": _state_percentile(bounds, counts, 0.99),
            }
        return out

    # --- Prometheus text exposition --------------------------------------

    def render_prometheus(self, prefix: str = "byteps_") -> str:
        """Text exposition format 0.0.4.  Histograms export the classic
        ``_bucket``/``_sum``/``_count`` family PLUS ``_p50``/``_p90``/
        ``_p99`` gauges so a bare ``curl`` (no PromQL) already answers
        "how slow is the tail"."""
        lines: List[str] = []
        flat = self.counters.snapshot()
        labeled = self.counters.snapshot_labeled()
        for name in sorted(flat):
            metric = f"{prefix}{name}_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {flat[name]}")
            if labeled.get(name):
                # the per-label breakdown is a SEPARATE family: the flat
                # total already includes the labeled bumps, so exporting
                # both under one name would make sum() double-count
                # (Prometheus series of one metric must be label-disjoint)
                lmetric = f"{prefix}{name}_labeled_total"
                lines.append(f"# TYPE {lmetric} counter")
                for lkey in sorted(labeled[name]):
                    lines.append(
                        f"{lmetric}{_render_labels(lkey)} {labeled[name][lkey]}"
                    )
        with self._lock:
            gauges = dict(self._gauges)
            gauge_fns = dict(self._gauge_fns)
        for gkey, fn in gauge_fns.items():
            try:
                gauges[gkey] = float(fn())
            except Exception:  # noqa: BLE001
                continue
        # label combinations group under one TYPE line per family, like
        # the histogram exposition below
        g_fams: Dict[str, List[Tuple[tuple, float]]] = {}
        for (name, lkey), v in gauges.items():
            g_fams.setdefault(name, []).append((lkey, v))
        for name in sorted(g_fams):
            metric = f"{prefix}{name}"
            lines.append(f"# TYPE {metric} gauge")
            for lkey, v in sorted(g_fams[name]):
                lines.append(f"{metric}{_render_labels(lkey)} {v}")
        # combined local + provider histograms (native_* families merge
        # into the same exposition the Python engines feed)
        by_family: Dict[str, List[Tuple[tuple, list]]] = {}
        for (name, lkey), st in self._hist_states().items():
            by_family.setdefault(name, []).append((lkey, st))
        for name in sorted(by_family):
            metric = f"{prefix}{name}"
            lines.append(f"# TYPE {metric} histogram")
            for lkey, (bounds, counts, vsum, count) in sorted(
                by_family[name], key=lambda kv: kv[0]
            ):
                cum = 0
                for le, c in zip(bounds, counts):
                    cum += c
                    labels = dict(lkey) | {"le": repr(float(le))}
                    lines.append(
                        f"{metric}_bucket{_render_labels(_label_key(labels))} {cum}"
                    )
                labels = dict(lkey) | {"le": "+Inf"}
                lines.append(
                    f"{metric}_bucket{_render_labels(_label_key(labels))} {count}"
                )
                lines.append(f"{metric}_sum{_render_labels(lkey)} {vsum}")
                lines.append(f"{metric}_count{_render_labels(lkey)} {count}")
            for q, tag in ((0.50, "p50"), (0.90, "p90"), (0.99, "p99")):
                qmetric = f"{metric}_{tag}"
                lines.append(f"# TYPE {qmetric} gauge")
                for lkey, (bounds, counts, _vsum, _count) in sorted(
                    by_family[name], key=lambda kv: kv[0]
                ):
                    lines.append(
                        f"{qmetric}{_render_labels(lkey)} "
                        f"{_state_percentile(bounds, counts, q)}"
                    )
        return "\n".join(lines) + "\n"

    # --- heartbeat delta piggyback (worker/server → scheduler) -----------

    def delta_snapshot(self) -> dict:
        """Counter/histogram increments since the previous call — the
        payload piggybacked on the scheduler heartbeat.  One consumer per
        process (the heartbeat loop); gauges are sent as current values.
        Empty dict when nothing changed (the heartbeat then ships no
        payload at all)."""
        with self._delta_lock:
            return self._delta_snapshot_locked()

    def _delta_snapshot_locked(self) -> dict:
        out: dict = {}
        flat = self.counters.snapshot()
        labeled = self.counters.snapshot_labeled()
        c_delta = {}
        for name, v in flat.items():
            d = v - self._shipped_counts.get(name, 0)
            if d:
                c_delta[name] = d
        if c_delta:
            out["c"] = c_delta
        lc_delta: Dict[str, Dict[str, int]] = {}
        for name, per in labeled.items():
            shipped = self._shipped_labeled.get(name, {})
            for lkey, v in per.items():
                d = v - shipped.get(lkey, 0)
                if d:
                    lc_delta.setdefault(name, {})[json.dumps(lkey)] = d
        if lc_delta:
            out["lc"] = lc_delta
        # combined local + provider histograms: the native engines'
        # families ride the same heartbeat deltas toward the scheduler
        # aggregate as everything else
        h_delta = []
        for (name, lkey), st in self._hist_states().items():
            bounds, raw, vsum, count = st
            prev = self._shipped_hists.get(
                (name, lkey), ([0] * len(raw), 0.0, 0)
            )
            d_counts = [a - b for a, b in zip(raw, prev[0])]
            d_count = count - prev[2]
            if d_count < 0 or any(d < 0 for d in d_counts):
                # a provider is mid-absorb (popped from the registry but
                # not yet folded into local histograms): combined totals
                # transiently went backwards.  Ship nothing and KEEP the
                # old baseline — the fold restores the totals, and the
                # next beat's delta stays exact.  Lowering the baseline
                # here would re-ship the provider's whole history.
                continue
            if d_count > 0:
                h_delta.append({
                    "name": name,
                    "l": [list(kv) for kv in lkey],
                    "le": list(bounds),
                    "b": d_counts,
                    "s": vsum - prev[1],
                    "n": d_count,
                })
            self._shipped_hists[(name, lkey)] = (raw, vsum, count)
        if h_delta:
            out["h"] = h_delta
        # gauges ship as CURRENT values when they changed (or appeared)
        # since the last beat, plus removal markers for series a stopping
        # source dropped — so the scheduler aggregate tracks e.g. each
        # server's owned-key count through a migration without a dead
        # rank's frozen gauge lingering (docs/observability.md)
        with self._lock:
            cur = dict(self._gauges)
            for key, fn in self._gauge_fns.items():
                try:
                    cur[key] = float(fn())
                except Exception:  # noqa: BLE001 — broken gauge ≠ broken beat
                    continue
        g_delta = [
            {"n": name, "l": [list(kv) for kv in lkey], "v": v}
            for (name, lkey), v in cur.items()
            if self._shipped_gauges.get((name, lkey)) != v
        ]
        if g_delta:
            out["g"] = g_delta
        gone = [
            {"n": name, "l": [list(kv) for kv in lkey]}
            for (name, lkey) in self._shipped_gauges
            if (name, lkey) not in cur
        ]
        if gone:
            out["gr"] = gone
        self._shipped_gauges = cur
        self._shipped_counts = flat
        self._shipped_labeled = labeled
        # fold back any delta whose heartbeat FAILED to send: its
        # increments were already consumed from the baselines above and
        # must ride the next successful beat, not vanish
        requeued, self._requeued = self._requeued, []
        for old in requeued:
            for name, d in (old.get("c") or {}).items():
                out.setdefault("c", {})
                out["c"][name] = out["c"].get(name, 0) + int(d)
            for name, per in (old.get("lc") or {}).items():
                dst = out.setdefault("lc", {}).setdefault(name, {})
                for lkey_json, d in per.items():
                    dst[lkey_json] = dst.get(lkey_json, 0) + int(d)
            if old.get("h"):
                # merge_delta adds records independently, so duplicate
                # (name, labels) entries in one payload sum correctly
                out.setdefault("h", []).extend(old["h"])
            # gauges are current-value: requeued records go FIRST so a
            # fresher value of the same series in this beat wins — and a
            # requeued record is DROPPED outright when this beat carries
            # the opposite kind for the same series (the receiver applies
            # all "g" then all "gr" per payload, so a stale requeued
            # removal would otherwise delete a series that just
            # reappeared, and a stale requeued value would resurrect one
            # that was just removed)
            fresh = {
                field: {
                    (r.get("n"), tuple(map(tuple, r.get("l") or ())))
                    for r in out.get(field) or ()
                }
                for field in ("g", "gr")
            }
            for field, opposite in (("g", "gr"), ("gr", "g")):
                keep = [
                    r for r in old.get(field) or ()
                    if (r.get("n"), tuple(map(tuple, r.get("l") or ())))
                    not in fresh[opposite]
                ]
                if keep:
                    out[field] = keep + list(out.get(field, []))
        return out

    def reship_for(self, token) -> bool:
        """Re-arm the delta baselines so the NEXT :meth:`delta_snapshot`
        ships the FULL history (counters, labeled slices, histograms)
        and re-registers every gauge — called when the heartbeat
        consumer changed identity (a new scheduler incarnation whose
        aggregate started empty; the dead one took the old baselines'
        aggregate to its grave, docs/robustness.md "Control-plane
        recovery").

        Idempotent per ``token``: in-process test fleets run several
        beat loops (worker + servers) against ONE shared registry, and
        only the first loop to observe the new incarnation may rebase —
        a second rebase would re-ship increments the first full
        snapshot already delivered, double-counting them in the new
        aggregate.  Returns True when the rebase actually happened.
        Requeued failed-send deltas are dropped (their increments are
        subsumed by the full re-ship)."""
        with self._delta_lock:
            if token == self._reship_token:
                return False
            self._reship_token = token
            self._requeued.clear()
            self._shipped_counts.clear()
            self._shipped_labeled.clear()
            self._shipped_hists.clear()
            self._shipped_gauges = {}
            return True

    def requeue_delta(self, delta: dict) -> None:
        """Give back a delta whose send failed; the next
        :meth:`delta_snapshot` includes it (at-least-once delivery of
        increments toward the scheduler aggregate)."""
        if not delta:
            return
        with self._delta_lock:
            self._requeued.append(delta)

    def merge_delta(self, delta: dict,
                    labels: Optional[Dict[str, str]] = None) -> None:
        """Fold one node's delta into this (scheduler-side aggregate)
        registry.  ``labels`` (e.g. {"role": "worker", "rank": "1"}) tag
        the counter contributions so a sick node stays visible in the
        aggregate; histograms merge flat (cluster-wide latency shape)."""
        for name, d in (delta.get("c") or {}).items():
            self.counters.bump(str(name), int(d), labels=labels)
        for name, per in (delta.get("lc") or {}).items():
            for lkey_json, d in per.items():
                try:
                    node_labels = dict(tuple(kv) for kv in json.loads(lkey_json))
                except (ValueError, TypeError):
                    node_labels = {}
                if labels:
                    node_labels.update(labels)
                # flat=False: the unlabeled "c" delta above already
                # carried these bumps — re-adding would double-count
                self.counters.bump(
                    str(name), int(d), labels=node_labels, flat=False
                )
        for rec in delta.get("h") or ():
            try:
                bounds = tuple(float(b) for b in rec["le"])
                node_labels = dict(tuple(kv) for kv in rec.get("l") or ())
                h = self.histogram(
                    str(rec["name"]), labels=node_labels or None,
                    buckets=bounds,
                )
                h.merge_counts(
                    [int(c) for c in rec["b"]], float(rec["s"]), int(rec["n"])
                )
            except (KeyError, ValueError, TypeError):
                continue  # malformed delta: drop, never poison the scrape
        # gauges: current values, node labels merged with the sender tag
        # (so cluster_map_epoch sits next to each server's
        # server_owned_keys{rank} in the bps_top view); "gr" drops series
        # a stopping source removed (a drained server's owned-key gauge)
        for rec in delta.get("g") or ():
            try:
                node_labels = dict(tuple(kv) for kv in rec.get("l") or ())
                if labels:
                    node_labels.update(labels)
                self.gauge_set(
                    str(rec["n"]), float(rec["v"]),
                    labels=node_labels or None,
                )
            except (KeyError, ValueError, TypeError):
                continue
        for rec in delta.get("gr") or ():
            try:
                node_labels = dict(tuple(kv) for kv in rec.get("l") or ())
                if labels:
                    node_labels.update(labels)
                self.gauge_remove(str(rec["n"]), labels=node_labels or None)
            except (KeyError, ValueError, TypeError):
                continue


class MetricsHTTPServer:
    """Tiny threaded HTTP exposition server for one render callback.

    Binds ``port`` (0 = ephemeral); when the requested port is taken —
    several byteps processes sharing one host and one
    ``BYTEPS_METRICS_PORT`` — falls back to an ephemeral port and logs
    the actual one, so every process still gets a scrape surface.
    """

    def __init__(self, port: int, render: Callable[[], str],
                 host: str = "0.0.0.0") -> None:
        import http.server

        render_fn = render

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    body = render_fn().encode()
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(repr(e).encode())
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        try:
            self._httpd = http.server.ThreadingHTTPServer((host, port), _Handler)
        except OSError:
            from byteps_tpu.common import logging as bpslog

            self._httpd = http.server.ThreadingHTTPServer((host, 0), _Handler)
            bpslog.warning(
                "BYTEPS_METRICS_PORT=%d in use; serving metrics on %d instead",
                port, self._httpd.server_address[1],
            )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="bps-metrics-http",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


def serve_metrics(port: int, render: Optional[Callable[[], str]] = None,
                  host: str = "0.0.0.0") -> MetricsHTTPServer:
    """Start the Prometheus exposition endpoint; default renders the
    process-global registry."""
    return MetricsHTTPServer(
        port, render if render is not None else metrics().render_prometheus,
        host=host,
    )


_counters = RobustnessCounters()
_registry = MetricsRegistry(counter_store=_counters)


def counters() -> RobustnessCounters:
    """The process-global robustness counter set."""
    return _counters


def metrics() -> MetricsRegistry:
    """The process-global metrics registry (counters + gauges +
    histograms behind one scrape surface)."""
    return _registry
