"""Push/pull speed telemetry + robustness counters.

Re-design of ``BytePSGlobal::PushPullSpeed`` (global.cc:697-752): a windowed
MB/s counter over recent push_pull byte volume, exposed to Python as
``bps.get_pushpull_speed()`` (common/__init__.py:131-139).  Gate:
``BYTEPS_TELEMETRY_ON``.

The robustness counters (:func:`counters`) make data-plane degradation
observable: every retry, deadline expiry, connection revival, server-side
duplicate-push suppression, chaos-van injected fault, and membership
eviction bumps a named counter.  They are process-global and always on —
a counter bump is one dict update under a lock, and the self-healing
paths they instrument are rare by construction.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Tuple

WINDOW_SEC = 10.0  # reference uses a 10-second window (global.cc:703)


class PushPullSpeed:
    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: Deque[Tuple[float, int]] = deque()
        self._total_bytes = 0

    def record(self, nbytes: int) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            self._events.append((now, nbytes))
            self._total_bytes += nbytes
            self._evict(now)

    def _evict(self, now: float) -> None:
        while self._events and now - self._events[0][0] > WINDOW_SEC:
            _, nb = self._events.popleft()
            self._total_bytes -= nb

    def mbps(self) -> float:
        """Windowed MB/s (returns 0 when disabled or idle)."""
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            if not self._events:
                return 0.0
            span = max(now - self._events[0][0], 1e-6)
            return self._total_bytes / span / 1e6


class RobustnessCounters:
    """Named monotonic counters for data-plane degradation events.

    Canonical names (consumers may add others):

    - ``rpc_retry``            — a push/pull/init attempt was re-sent
    - ``rpc_deadline_expired`` — a per-RPC deadline fired (hung server)
    - ``rpc_giveup``           — retries exhausted; error surfaced
    - ``conn_revive``          — a dead server connection was rebuilt
    - ``push_dedup``           — server suppressed a replayed push
    - ``degraded_jobs``        — engine jobs failed with DegradedError
    - ``worker_evicted`` / ``server_evicted`` — evictions observed from
      the scheduler's membership broadcasts (cumulative)
    - ``chaos_drop`` / ``chaos_delay`` / ``chaos_disconnect`` /
      ``chaos_truncate`` / ``chaos_corrupt`` — injected faults

    Small-tensor fusion (docs/perf.md):

    - ``wire_rpc``             — data-plane frames actually sent (every
      async push/pull/fused attempt, retries included) — the denominator
      ``tools/fusion_bench.py`` compares fused vs. unfused
    - ``fused_frames``         — multi-key Op.FUSED frames shipped
    - ``fused_keys``           — member partitions carried by those frames
      (``fused_keys / fused_frames`` = achieved pack density)
    - ``fusion_flush_full`` / ``fusion_flush_idle`` /
      ``fusion_flush_cycle`` — why each pack left the buffer (capacity
      reached / pipeline drained / BYTEPS_FUSION_CYCLE_MS backstop) —
      the first knob to read when tuning threshold vs. cycle
    - ``fused_fallback``       — packs downgraded to per-key unfused
      RPCs (server resize under the pack, or fused retries exhausted)
    - ``fused_reply_malformed`` — fused replies that failed to decode
      (routed to the frame's error path instead of the recv lane)
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def set_floor(self, name: str, value: int) -> None:
        """Raise a counter to ``value`` if below it — used for cumulative
        totals observed from broadcasts, which may be re-delivered."""
        with self._lock:
            if self._counts.get(name, 0) < value:
                self._counts[name] = value

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()


_counters = RobustnessCounters()


def counters() -> RobustnessCounters:
    """The process-global robustness counter set."""
    return _counters
