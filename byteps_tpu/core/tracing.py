"""Chrome-trace timeline of communication stages + distributed spans.

Two event families share one tracer (docs/observability.md):

- **Stage envelopes** (:meth:`Tracer.record`) — the reference's tracing
  subsystem (global.cc:448-564, docs/timeline.md): per named tensor, per
  pipeline stage, ``{start, duration}`` intervals between
  trace_start_step and trace_end_step, one trace row per tensor.
- **Spans** (:meth:`Tracer.record_span`) — cross-process distributed
  tracing: every engine task gets a (trace id, span id) pair, the ids
  ride each framed RPC in the optional trace-context header field
  (transport.py), and the server stamps child spans
  (recv→sum→publish→reply) that share the worker's trace id.
  ``tools/trace_merge.py`` stitches the per-process files into one
  Perfetto-loadable timeline joined on those ids.

Emission is ``<dir>/<local_rank>/comm.json`` in Chrome trace-event
format (load via chrome://tracing or Perfetto).  ``flush()`` writes the
CURRENT window and clears the buffer, so ``profiler.trace()`` can
capture any number of windows per process (the pre-observability tracer
had a one-shot latch: the second flush silently dropped all events).

Host stages are stamped by the pipeline engine; device-side collective
timing is XLA's domain (use jax.profiler for that) — the tracer records
the host-visible envelope, which is what the reference records too.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

_id_rng = random.SystemRandom()


def new_trace_id() -> int:
    """Nonzero 63-bit id for a trace or span.  SystemRandom: training
    code may have seeded the global RNG for data order, and two workers
    seeding identically must never mint colliding trace ids."""
    return _id_rng.getrandbits(63) | 1


def span_args(trace_id: int, span_id: int, parent_id: int = 0,
              **extra) -> dict:
    """Canonical args dict for a span event — hex strings so Perfetto's
    JSON importer (which parses large ints as doubles) never rounds an
    id."""
    args = {"trace": format(trace_id, "x"), "span": format(span_id, "x")}
    if parent_id:
        args["parent"] = format(parent_id, "x")
    args.update(extra)
    return args


class Tracer:
    #: in-memory event cap: span events are window-free, so a long run
    #: with tracing on must not grow the buffer unboundedly — beyond the
    #: cap new events are dropped (counted; flush logs the loss)
    MAX_EVENTS = 1 << 18

    def __init__(
        self,
        enabled: bool = False,
        start_step: int = 10,
        end_step: int = 20,
        trace_dir: str = ".",
        local_rank: int = 0,
        process_name: str = "",
        spans_enabled: bool = True,
    ) -> None:
        self.enabled = enabled
        self.start_step = start_step
        self.end_step = end_step
        self.trace_dir = trace_dir
        self.local_rank = local_rank
        #: BYTEPS_TRACE_SPANS gate: False keeps the per-tensor stage
        #: envelopes but drops span events (and wire trace context)
        self.spans_enabled = spans_enabled
        #: cross-process identity stamped on span events ("worker0",
        #: "server1"); set once the scheduler assigns a rank
        self.process_name = process_name or f"rank{local_rank}"
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._dropped = 0  # events past MAX_EVENTS since the last flush
        self._steps: Dict[str, int] = {}  # per-tensor version counter

    def _active(self, step: int) -> bool:
        return self.enabled and self.start_step <= step <= self.end_step

    def step_of(self, name: str) -> int:
        with self._lock:
            return self._steps.get(name, 0)

    def bump_step(self, name: str) -> int:
        with self._lock:
            s = self._steps.get(name, 0) + 1
            self._steps[name] = s
            return s

    def _append_locked(self, event: dict) -> None:
        """Caller holds ``self._lock``.  Enforces MAX_EVENTS: a capped
        buffer drops (and counts) instead of growing until OOM — span
        events have no step window, so a long tracing-on run would
        otherwise accumulate forever between flushes."""
        if len(self._events) >= self.MAX_EVENTS:
            self._dropped += 1
            return
        self._events.append(event)

    def record(self, name: str, stage: str, start: float, dur: float, step: int) -> None:
        """One complete-event per (tensor, stage) interval
        (global.cc:478-530 emits type 'X' events keyed the same way)."""
        if not self._active(step):
            return
        with self._lock:
            self._append_locked(
                {
                    "name": stage,
                    "cat": "comm",
                    "ph": "X",
                    "ts": start * 1e6,
                    "dur": dur * 1e6,
                    "pid": name,  # one trace row per tensor, like the reference
                    "tid": stage,
                }
            )

    # --- distributed spans (docs/observability.md) -----------------------

    def record_span(self, track: str, name: str, start: float, dur: float,
                    args: Optional[dict] = None) -> None:
        """One complete-event span on this process's timeline.  ``track``
        groups related spans on one row (tensor name, "engine", …);
        ``args`` should come from :func:`span_args` so merge joins work.
        Timestamps are wall-clock (``time.time()``) so per-host worker
        and server spans align on one merged timeline."""
        if not self.enabled or not self.spans_enabled:
            return
        with self._lock:
            self._append_locked(
                {
                    "name": name,
                    "cat": "span",
                    "ph": "X",
                    "ts": start * 1e6,
                    "dur": dur * 1e6,
                    "pid": self.process_name,
                    "tid": track,
                    "args": args or {},
                }
            )

    def record_instant(self, track: str, name: str,
                       args: Optional[dict] = None,
                       ts: Optional[float] = None) -> None:
        """Zero-duration marker (chaos fault tags, eviction moments)."""
        if not self.enabled or not self.spans_enabled:
            return
        with self._lock:
            self._append_locked(
                {
                    "name": name,
                    "cat": "span",
                    "ph": "i",
                    "s": "t",  # thread-scoped instant
                    "ts": (time.time() if ts is None else ts) * 1e6,
                    "pid": self.process_name,
                    "tid": track,
                    "args": args or {},
                }
            )

    def pending_events(self) -> int:
        with self._lock:
            return len(self._events)

    def flush(self) -> str:
        """Write the current window and clear the buffer; returns the
        output path, or "" when disabled or nothing was recorded.
        Multiple windows per process are supported: each
        ``profiler.trace()`` exit flushes its own window.  A window
        NEVER clobbers an earlier one — when ``comm.json`` already
        exists in the target directory (e.g. the shutdown flush landing
        in a dir a profiler window already used), the new window goes to
        ``comm.<n>.json``; ``tools/trace_merge.py`` globs ``comm*.json``
        so every window joins the merged timeline."""
        if not self.enabled:
            return ""
        with self._lock:
            if not self._events:
                return ""
            events, self._events = self._events, []
            dropped, self._dropped = self._dropped, 0
        if dropped:
            from byteps_tpu.common import logging as bpslog

            bpslog.warning(
                "tracer dropped %d events past the %d-event buffer cap "
                "(flush more often, or narrow the trace window)",
                dropped, self.MAX_EVENTS,
            )
        out_dir = os.path.join(self.trace_dir, str(self.local_rank))
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "comm.json")
        n = 2
        while os.path.exists(path):
            path = os.path.join(out_dir, f"comm.{n}.json")
            n += 1
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            payload["otherData"] = {"dropped_events": dropped}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path


class StageTimer:
    """Context manager stamping one stage interval onto a tracer."""

    def __init__(self, tracer: Tracer, name: str, stage: str, step: int) -> None:
        self.tracer = tracer
        self.name = name
        self.stage = stage
        self.step = step

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.tracer.record(self.name, self.stage, self.t0, time.time() - self.t0, self.step)
        return False


#: process-global tracer — set by init_state (workers) / PSServer
#: (servers) so layers without runtime-state access (chaos van, PS
#: client) can stamp events on the owning process's timeline
_process_tracer: Optional[Tracer] = None


def set_process_tracer(tracer: Optional[Tracer]) -> None:
    global _process_tracer
    _process_tracer = tracer


def get_process_tracer() -> Optional[Tracer]:
    return _process_tracer
