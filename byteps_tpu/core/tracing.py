"""Chrome-trace timeline of communication stages.

Re-design of the reference's tracing subsystem (global.cc:448-564,
docs/timeline.md): per named tensor, per pipeline stage, record
``{start, duration}`` intervals between trace_start_step and trace_end_step
and emit ``<dir>/<local_rank>/comm.json`` in Chrome trace-event format
(load via chrome://tracing or Perfetto).

Host stages are stamped by the pipeline engine; device-side collective
timing is XLA's domain (use jax.profiler for that) — the tracer records the
host-visible envelope, which is what the reference records too.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List


class Tracer:
    def __init__(
        self,
        enabled: bool = False,
        start_step: int = 10,
        end_step: int = 20,
        trace_dir: str = ".",
        local_rank: int = 0,
    ) -> None:
        self.enabled = enabled
        self.start_step = start_step
        self.end_step = end_step
        self.trace_dir = trace_dir
        self.local_rank = local_rank
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._steps: Dict[str, int] = {}  # per-tensor version counter
        self._flushed = False

    def _active(self, step: int) -> bool:
        return self.enabled and self.start_step <= step <= self.end_step

    def step_of(self, name: str) -> int:
        with self._lock:
            return self._steps.get(name, 0)

    def bump_step(self, name: str) -> int:
        with self._lock:
            s = self._steps.get(name, 0) + 1
            self._steps[name] = s
            return s

    def record(self, name: str, stage: str, start: float, dur: float, step: int) -> None:
        """One complete-event per (tensor, stage) interval
        (global.cc:478-530 emits type 'X' events keyed the same way)."""
        if not self._active(step):
            return
        with self._lock:
            self._events.append(
                {
                    "name": stage,
                    "cat": "comm",
                    "ph": "X",
                    "ts": start * 1e6,
                    "dur": dur * 1e6,
                    "pid": name,  # one trace row per tensor, like the reference
                    "tid": stage,
                }
            )

    def flush(self) -> str:
        if not self.enabled or self._flushed:
            return ""
        out_dir = os.path.join(self.trace_dir, str(self.local_rank))
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, "comm.json")
        with self._lock:
            payload = {"traceEvents": self._events, "displayTimeUnit": "ms"}
        with open(path, "w") as f:
            json.dump(payload, f)
        self._flushed = True
        return path


class StageTimer:
    """Context manager stamping one stage interval onto a tracer."""

    def __init__(self, tracer: Tracer, name: str, stage: str, step: int) -> None:
        self.tracer = tracer
        self.name = name
        self.stage = stage
        self.step = step

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.tracer.record(self.name, self.stage, self.t0, time.time() - self.t0, self.step)
        return False
