"""CrossBarrier equivalent — pipelined per-parameter optimizer.

Re-design of torch/cross_barrier.py (SURVEY §2.5): the reference removes
the per-step global barrier and re-implements sgd/adam/rmsprop so each
parameter updates the moment ITS gradient arrives (per-param locks +
poller thread), letting step N+1's forward start while low-priority
gradients still sync.

On TPU the in-step overlap is XLA's job; this host-side class provides the
same semantics for the PS/DCN path: ``backward(grads)`` launches one async
push_pull per parameter (priority = −declaration order, so front-layer
params sync first), and ``wait(name)`` / ``step()`` apply updates lazily —
callers that consume parameters front-to-back (the next forward pass)
never wait on back-layer gradients.
"""

from __future__ import annotations

import threading
from typing import Dict

import numpy as np

import byteps_tpu as bps


class _SGD:
    def __init__(self, lr: float, momentum: float = 0.0, weight_decay: float = 0.0):
        self.lr, self.mu, self.wd = lr, momentum, weight_decay
        self.state: Dict[str, np.ndarray] = {}

    def update(self, name, param, grad):
        if self.wd:
            grad = grad + self.wd * param
        if self.mu:
            m = self.state.get(name)
            m = grad if m is None else self.mu * m + grad
            self.state[name] = m
            grad = m
        return param - self.lr * grad


class _Adam:
    def __init__(self, lr: float, betas=(0.9, 0.999), eps: float = 1e-8, weight_decay: float = 0.0):
        self.lr, self.b1, self.b2, self.eps, self.wd = lr, betas[0], betas[1], eps, weight_decay
        self.m: Dict[str, np.ndarray] = {}
        self.v: Dict[str, np.ndarray] = {}
        self.t: Dict[str, int] = {}

    def update(self, name, param, grad):
        if self.wd:
            grad = grad + self.wd * param
        t = self.t.get(name, 0) + 1
        self.t[name] = t
        m = self.b1 * self.m.get(name, np.zeros_like(grad)) + (1 - self.b1) * grad
        v = self.b2 * self.v.get(name, np.zeros_like(grad)) + (1 - self.b2) * grad**2
        self.m[name], self.v[name] = m, v
        mhat = m / (1 - self.b1**t)
        vhat = v / (1 - self.b2**t)
        return param - self.lr * mhat / (np.sqrt(vhat) + self.eps)


class _RMSProp:
    def __init__(self, lr: float, alpha: float = 0.99, eps: float = 1e-8, weight_decay: float = 0.0):
        self.lr, self.alpha, self.eps, self.wd = lr, alpha, eps, weight_decay
        self.sq: Dict[str, np.ndarray] = {}

    def update(self, name, param, grad):
        if self.wd:
            grad = grad + self.wd * param
        sq = self.alpha * self.sq.get(name, np.zeros_like(grad)) + (1 - self.alpha) * grad**2
        self.sq[name] = sq
        return param - self.lr * grad / (np.sqrt(sq) + self.eps)


_OPTS = {"sgd": _SGD, "adam": _Adam, "rmsprop": _RMSProp}


class CrossBarrierOptimizer:
    """Per-parameter pipelined optimizer over async push_pull handles.

    Supported opt_name: sgd | adam | rmsprop (the three the reference
    re-implements, cross_barrier.py:28-425).
    """

    def __init__(
        self,
        params: Dict[str, np.ndarray],
        opt_name: str = "sgd",
        average: bool = True,
        **opt_kwargs,
    ) -> None:
        if opt_name not in _OPTS:
            raise ValueError(f"unsupported optimizer {opt_name!r}; use one of {list(_OPTS)}")
        self.params = {k: np.asarray(v, dtype=np.float32) for k, v in params.items()}
        self.opt = _OPTS[opt_name](**opt_kwargs)
        self.average = average
        self._order = {name: i for i, name in enumerate(self.params)}
        self._handles: Dict[str, int] = {}
        self._lock = threading.Lock()
        for name in self.params:
            bps.declare_tensor(f"Gradient.{name}")

    def backward(self, grads: Dict[str, np.ndarray]) -> None:
        """Launch async push_pull for every gradient; returns immediately
        (the hook behavior, cross_barrier.py:120-160).  A still-outstanding
        handle for the same parameter is synchronized-and-applied first so
        no gradient is ever dropped and no handle leaks."""
        for name in grads:
            self.wait(name)
        with self._lock:
            for name, g in grads.items():
                self._handles[name] = bps.push_pull_async(
                    np.asarray(g, dtype=np.float32),
                    name=f"Gradient.{name}",
                    average=self.average,
                    priority=-self._order[name],
                )

    def wait(self, name: str) -> np.ndarray:
        """Block until THIS parameter's gradient arrived, apply its update,
        return the fresh value (per-param lock semantics)."""
        with self._lock:
            handle = self._handles.pop(name, None)
        if handle is not None:
            grad = np.asarray(bps.synchronize(handle))
            self.params[name] = self.opt.update(name, self.params[name], grad)
        return self.params[name]

    def step(self) -> Dict[str, np.ndarray]:
        """Apply all outstanding updates (a full barrier — what the
        reference's plain DistributedOptimizer would do every step)."""
        for name in list(self.params):
            self.wait(name)
        return self.params
