"""Data input utilities: worker sharding + device prefetch.

The reference delegates input pipelines to the frameworks; for the TPU
build the two pieces worth owning are:

- :func:`shard_for_worker` / :class:`ShardedDataset` — deterministic
  per-worker (and per-epoch shuffled) sharding of an index space, the
  cross-host analogue of the reference's per-GPU samplers.
- :func:`prefetch_to_device` — a double-buffered host→device pipeline so
  the next batch's H2D transfer overlaps the current step (the D2H/H2D
  overlap the reference builds with CUDA copy streams, done here with
  jax async dispatch).
"""

from __future__ import annotations

import collections
from typing import Any, Iterable, Iterator, Optional

import jax
import numpy as np


def shard_for_worker(
    num_examples: int,
    worker_rank: Optional[int] = None,
    num_workers: Optional[int] = None,
    seed: int = 0,
    shuffle: bool = True,
    drop_remainder: bool = True,
) -> np.ndarray:
    """Indices owned by this worker: shuffle globally (same seed on every
    worker), then stride-partition so shards are disjoint and balanced."""
    import byteps_tpu as bps

    rank = bps.rank() if worker_rank is None else worker_rank
    world = bps.size() if num_workers is None else num_workers
    idx = np.arange(num_examples)
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    if drop_remainder:
        per = num_examples // world
        idx = idx[: per * world]
    return idx[rank::world]


class ShardedDataset:
    """Minimal epoch iterator over (x, y, ...) arrays, sharded per worker.

    Reshuffles every epoch with seed = base_seed + epoch (identical
    permutation on every worker, disjoint shards)."""

    def __init__(
        self,
        arrays,
        batch_size: int,
        seed: int = 0,
        worker_rank: Optional[int] = None,
        num_workers: Optional[int] = None,
    ) -> None:
        self.arrays = tuple(np.asarray(a) for a in arrays)
        n = {len(a) for a in self.arrays}
        if len(n) != 1:
            raise ValueError(f"arrays disagree on length: {n}")
        self.num_examples = n.pop()
        self.batch_size = batch_size
        self.seed = seed
        self.worker_rank = worker_rank
        self.num_workers = num_workers

    def epoch(self, epoch: int = 0) -> Iterator[tuple]:
        idx = shard_for_worker(
            self.num_examples, self.worker_rank, self.num_workers,
            seed=self.seed + epoch,
        )
        for i in range(0, len(idx) - self.batch_size + 1, self.batch_size):
            sel = idx[i : i + self.batch_size]
            yield tuple(a[sel] for a in self.arrays)


def prefetch_to_device(
    iterator: Iterable,
    size: int = 2,
    sharding: Optional[Any] = None,
) -> Iterator:
    """Keep ``size`` batches in flight on device.

    ``jax.device_put`` is async; holding a small deque of already-
    transferred batches lets the H2D DMA of batch N+1 overlap step N's
    compute — the role the reference's dedicated CUDA copy streams play
    (global.cc:253-268)."""

    put = (
        (lambda b: jax.device_put(b, sharding))
        if sharding is not None
        else jax.device_put
    )
    it = iter(iterator)
    if size <= 0:  # prefetch disabled: plain pass-through transfer
        for b in it:
            yield put(b)
        return
    queue: collections.deque = collections.deque()
    try:
        for _ in range(size):
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield out
