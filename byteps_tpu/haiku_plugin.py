"""dm-haiku adapter.

The reference ships one thin plugin per framework (torch/tensorflow/
mxnet/keras, SURVEY §2.5); JAX-side the native API already covers flax
and raw-jax users, and this module gives haiku users the same one-liner
surface:

    params = hk.transform(net).init(rng, x)
    params = byteps_tpu.haiku_plugin.broadcast_parameters(params)
    step = byteps_tpu.haiku_plugin.build_train_step(loss_fn, optax.adam(1e-3))
"""

from __future__ import annotations

from typing import Callable

import optax

from byteps_tpu.api import broadcast_parameters  # noqa: F401 (re-export)
from byteps_tpu.comm.mesh import DP_AXIS
from byteps_tpu.optim import build_data_parallel_step, distributed_optimizer


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    axis_names=(DP_AXIS,),
    average: bool = True,
) -> optax.GradientTransformation:
    """Optax wrap usable with any haiku-transformed model (gradients are
    all-reduced across the data axes under shard_map)."""
    return distributed_optimizer(optimizer, axis_names, average)


def build_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh=None,
    donate: bool = True,
) -> Callable:
    """DDP step for a haiku apply-based ``loss_fn(params, batch)``."""
    return build_data_parallel_step(loss_fn, optimizer, mesh=mesh, donate=donate)
