"""dm-haiku adapter.

The reference ships one thin plugin per framework (torch/tensorflow/
mxnet/keras, SURVEY §2.5); JAX-side the native API already covers flax
and raw-jax users, and this module gives haiku users the same surface:

    net = hk.transform_with_state(forward)
    params, state = net.init(rng, x)
    params = byteps_tpu.haiku_plugin.broadcast_parameters(params)
    step = byteps_tpu.haiku_plugin.build_stateful_train_step(
        net.apply, loss_from_out, optax.adam(1e-3))
    (params, state), opt_state, loss = step((params, state), opt_state,
                                            rng, batch)

``build_stateful_train_step`` handles ``hk.transform_with_state``
networks (BatchNorm / moving averages): gradients AND the updated haiku
state are pmean'd over the dp axis — cross-replica statistics, the same
semantics as the flax variant in :mod:`byteps_tpu.optim`.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import optax
from jax import lax
from jax.sharding import Mesh

from byteps_tpu.api import broadcast_parameters  # noqa: F401 (re-export)
from byteps_tpu.comm.mesh import DP_AXIS
from byteps_tpu.optim import (
    _compile_spmd_step,
    _ddp_apply,
    _pmean_float_leaves,
    build_data_parallel_step,
    distributed_optimizer,
)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    axis_names=(DP_AXIS,),
    average: bool = True,
) -> optax.GradientTransformation:
    """Optax wrap usable with any haiku-transformed model (gradients are
    all-reduced across the data axes under shard_map)."""
    return distributed_optimizer(optimizer, axis_names, average)


def build_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh=None,
    donate: bool = True,
) -> Callable:
    """DDP step for a stateless ``hk.transform`` model:
    ``loss_fn(params, batch)`` scalar loss."""
    return build_data_parallel_step(loss_fn, optimizer, mesh=mesh, donate=donate)


def build_stateful_train_step(
    apply_fn: Callable,
    loss_from_out: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    axis_name: str = DP_AXIS,
    donate: bool = True,
) -> Callable:
    """DDP step for ``hk.transform_with_state`` models (BatchNorm-class
    mutable state).

    ``step((params, state), opt_state, rng, batch)`` →
    ``((params, state), opt_state, loss)``.  ``apply_fn`` is
    ``net.apply(params, state, rng, x) -> (out, new_state)``; gradients
    and the new state are pmean'd over the dp axis so every replica holds
    identical cross-replica statistics.
    """

    def local_step(bundle: Tuple[Any, Any], opt_state, rng, batch):
        params, state = bundle
        x, y = batch
        # per-replica rng: each dp shard must draw INDEPENDENT dropout/
        # noise masks for its examples, not replicate one mask pattern
        rng = jax.random.fold_in(rng, lax.axis_index(axis_name))

        def loss_fn(p):
            out, new_state = apply_fn(p, state, rng, x)
            return loss_from_out(out, y), new_state

        (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # cross-replica statistics: float leaves pmean'd, integer leaves
        # (EMA counters) pass through with their dtype intact
        new_state = _pmean_float_leaves(new_state, axis_name)
        params, opt_state, loss = _ddp_apply(
            grads, loss, params, opt_state, optimizer, axis_name
        )
        return (params, new_state), opt_state, loss

    return _compile_spmd_step(
        local_step, mesh, axis_name, donate, extra_replicated_args=1
    )
