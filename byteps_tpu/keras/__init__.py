"""Keras plugin — wraps the TF plugin for keras-native workflows.

Parity surface with byteps/keras/__init__.py:32-128 + _keras/__init__.py:
``DistributedOptimizer``, ``broadcast_global_variables``, ``push_pull``,
``broadcast``, and ``load_model`` (re-wrapping the saved optimizer so its
state continues training distributed, keras/__init__.py:94-128).
"""

from __future__ import annotations

from typing import Optional

import keras
import numpy as np
import tensorflow as tf

from byteps_tpu.api import (  # noqa: F401
    init,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from byteps_tpu.tensorflow import Compression  # noqa: F401
from byteps_tpu.tensorflow import DistributedOptimizer as _tf_distributed_optimizer
from byteps_tpu.tensorflow import broadcast as _tf_broadcast
from byteps_tpu.tensorflow import push_pull as _tf_push_pull
from byteps_tpu.keras import callbacks  # noqa: F401


def DistributedOptimizer(
    optimizer,
    name: Optional[str] = None,
    compression=Compression.none,
    scope: str = "opt",
):
    """Keras optimizer wrap (keras/__init__.py:32-57)."""
    return _tf_distributed_optimizer(
        optimizer, name=name, compression=compression, scope=scope
    )


def push_pull(value, name: Optional[str] = None, average: bool = True):
    """Reduce a tensor-compatible value across workers
    (keras/__init__.py:68-79)."""
    t = tf.constant(np.asarray(value))
    return np.asarray(_tf_push_pull(t, average=average, name=name))


def broadcast(value, root_rank: int = 0, name: Optional[str] = None):
    """Root's value everywhere (keras/__init__.py:82-93)."""
    t = tf.constant(np.asarray(value))
    return np.asarray(_tf_broadcast(t, root_rank, name=name))


def broadcast_global_variables(root_rank: int = 0) -> None:
    """Deprecated graph-mode API; in Keras 3 use
    ``callbacks.BroadcastGlobalVariablesCallback`` (the reference
    deprecates it the same way for TF2, tensorflow/__init__.py:95-110)."""
    raise RuntimeError(
        "broadcast_global_variables() requires graph-mode sessions; with "
        "Keras 3 use byteps_tpu.keras.callbacks.BroadcastGlobalVariablesCallback"
    )


def load_model(
    filepath,
    custom_optimizers=None,
    custom_objects=None,
    compression=Compression.none,
):
    """Load a saved Keras model with its optimizer re-wrapped as a
    DistributedOptimizer (keras/__init__.py:94-128).

    The saved config names the plain optimizer class (the wrapper reuses
    the wrapped class's name exactly so models saved with byteps restore
    without it); here we inject custom_objects mapping those names back to
    wrapping factories.
    """

    import os

    from byteps_tpu.tensorflow import Average, _wrap_keras_optimizer_class

    enable_async = int(os.getenv("BYTEPS_ENABLE_ASYNC", "0")) != 0

    def wrap_optimizer(cls):
        # Keras 3 deserialization instantiates via cls.from_config, so the
        # custom object must be an Optimizer CLASS — hand it the wrapped
        # subclass (same name as the original, from_config inherited).
        return _wrap_keras_optimizer_class(
            cls, compression, Average, "opt", enable_async
        )

    byteps_objects = {}
    for attr in dir(keras.optimizers):
        obj = getattr(keras.optimizers, attr)
        if (
            isinstance(obj, type)
            and issubclass(obj, keras.optimizers.Optimizer)
            and obj is not keras.optimizers.Optimizer
            and obj.__name__ not in byteps_objects
        ):
            wrapped = wrap_optimizer(obj)
            byteps_objects[obj.__name__] = wrapped
            byteps_objects[obj.__name__.lower()] = wrapped
    if custom_optimizers is not None:
        byteps_objects.update(
            {cls.__name__: wrap_optimizer(cls) for cls in custom_optimizers}
        )
    if custom_objects is not None:
        byteps_objects.update(custom_objects)
    return keras.models.load_model(filepath, custom_objects=byteps_objects)
