"""Keras-3-native callbacks (byteps/_keras/callbacks.py:23-195 parity).

- :class:`BroadcastGlobalVariablesCallback` — one-shot model+optimizer
  variable sync from root at train start.
- :class:`MetricAverageCallback` — average epoch metrics across workers.
- :class:`LearningRateScheduleCallback` / :class:`LearningRateWarmupCallback`
  — multiplier schedules and size-aware gradual warmup.

These subclass ``keras.callbacks.Callback`` so they drop straight into
``model.fit(callbacks=[...])``; the JAX-loop equivalents live in
:mod:`byteps_tpu.callbacks`.
"""

from __future__ import annotations

import math
from typing import Optional

import keras
import numpy as np

import byteps_tpu.tensorflow as bps


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast model + optimizer variables from root once, at the end of
    the first batch (after variables exist — the reference broadcasts
    on_batch_end for the same reason, _keras/callbacks.py:31-49)."""

    def __init__(self, root_rank: int = 0) -> None:
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done or bps.size() <= 1:
            return
        bps.broadcast_variables(self.model.weights, root_rank=self.root_rank)
        if getattr(self.model, "optimizer", None) is not None:
            bps.broadcast_variables(
                self.model.optimizer.variables, root_rank=self.root_rank
            )
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average logged metrics across workers at epoch end
    (_keras/callbacks.py:51-106): with one worker a no-op; metrics are
    reduced sorted-by-name so every worker issues the same op order."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or bps.size() <= 1:
            return
        import tensorflow as tf

        for metric in sorted(logs):
            value = logs[metric]
            if isinstance(value, (int, float, np.floating)):
                logs[metric] = float(
                    np.asarray(
                        bps.push_pull(
                            tf.constant(float(value), dtype=tf.float64),
                            name=f"Metric.{metric}",
                            average=True,
                        )
                    )
                )


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """lr(epoch) = initial_lr * multiplier(epoch) on
    [start_epoch, end_epoch) (_keras/callbacks.py:108-159)."""

    def __init__(
        self,
        initial_lr: float,
        multiplier,
        start_epoch: int = 0,
        end_epoch: Optional[int] = None,
        staircase: bool = True,
        steps_per_epoch: Optional[int] = None,
    ) -> None:
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self._fn = multiplier
        else:
            self._fn = lambda e: float(multiplier)

    def _lr(self, epoch: float) -> Optional[float]:
        if epoch < self.start_epoch:
            return None
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return None
        e = math.floor(epoch) if self.staircase else epoch
        return self.initial_lr * self._fn(e - self.start_epoch)

    def _set_lr(self, lr: float) -> None:
        self.model.optimizer.learning_rate.assign(lr)

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase or self.steps_per_epoch is None:
            lr = self._lr(epoch)
            if lr is not None:
                self._set_lr(lr)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase and self.steps_per_epoch is not None:
            lr = self._lr(self.current_epoch + batch / self.steps_per_epoch)
            if lr is not None:
                self._set_lr(lr)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = float(
                np.asarray(self.model.optimizer.learning_rate)
            )


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr/size to lr over ``warmup_epochs``
    (_keras/callbacks.py:161-195, the Goyal et al. recipe)."""

    def __init__(
        self,
        initial_lr: float,
        warmup_epochs: int = 5,
        momentum_correction: bool = False,
        steps_per_epoch: Optional[int] = None,
        verbose: int = 0,
    ) -> None:
        if momentum_correction:
            raise NotImplementedError(
                "momentum_correction: rescale optimizer momentum manually "
                "(m' = m * lr_new/lr_old per adjustment, as the reference does)"
            )
        self.warmup_epochs = warmup_epochs

        def mult(e: float) -> float:
            if warmup_epochs <= 0:
                return 1.0
            frac = min(1.0, (e + 1) / warmup_epochs)
            base = 1.0 / max(1, bps.size())
            return base + (1.0 - base) * frac

        super().__init__(
            initial_lr,
            mult,
            start_epoch=0,
            end_epoch=warmup_epochs,
            staircase=False,
            steps_per_epoch=steps_per_epoch,
        )
