"""Launcher & deployment (SURVEY §2.6)."""
