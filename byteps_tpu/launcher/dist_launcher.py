"""Multi-node SSH fan-out launcher.

Re-design of launcher/dist_launcher.py (SURVEY §2.6): reads host files for
workers and servers, SSHes ``bpslaunch`` onto every host with the proper
``DMLC_*`` role env, streams logs to ``sshlog/<host>.log``.  The scheduler
runs on the first server host (or ``--scheduler-host``).

Usage:
    python -m byteps_tpu.launcher.dist_launcher \
        --worker-hostfile workers.txt --server-hostfile servers.txt \
        [--scheduler-port 9000] [--env KEY=VAL ...] -- CMD [ARGS...]
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import threading
from typing import Dict, List, Optional


def read_hostfile(path: str) -> List[str]:
    with open(path) as f:
        return [ln.strip() for ln in f if ln.strip() and not ln.startswith("#")]


def build_role_env(
    role: str,
    rank: int,
    num_workers: int,
    num_servers: int,
    root_uri: str,
    root_port: int,
    extra: Dict[str, str],
) -> Dict[str, str]:
    """Per-role env exports (dist_launcher.py:55-90)."""
    env = {
        "DMLC_ROLE": role,
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_NUM_SERVER": str(num_servers),
        "DMLC_PS_ROOT_URI": root_uri,
        "DMLC_PS_ROOT_PORT": str(root_port),
    }
    if role == "worker":
        env["DMLC_WORKER_ID"] = str(rank)
        env["BYTEPS_GLOBAL_RANK"] = str(rank)
    env.update(extra)
    return env


def ssh_command(host: str, env: Dict[str, str], cmd: List[str]) -> List[str]:
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    remote = f"{exports} {' '.join(shlex.quote(c) for c in cmd)}"
    return [
        "ssh", "-o", "StrictHostKeyChecking=no", "-o", "BatchMode=yes",
        host, remote,
    ]


def _run_logged(argv: List[str], log_path: str) -> int:
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, "w") as log:
        return subprocess.call(argv, stdout=log, stderr=subprocess.STDOUT)


def main(args: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--worker-hostfile", required=True)
    p.add_argument("--server-hostfile", default="")
    p.add_argument("--scheduler-host", default="")
    p.add_argument("--scheduler-port", type=int, default=9000)
    p.add_argument("--env", action="append", default=[], metavar="KEY=VAL")
    p.add_argument("--log-dir", default="sshlog")
    p.add_argument(
        "--remote-python", default="python3",
        help="python executable on remote hosts (the local sys.executable "
        "path rarely exists remotely)",
    )
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    ns = p.parse_args(args)

    workers = read_hostfile(ns.worker_hostfile)
    servers = read_hostfile(ns.server_hostfile) if ns.server_hostfile else []
    cmd = ns.cmd[1:] if ns.cmd[:1] == ["--"] else ns.cmd
    if not cmd:
        raise SystemExit("dist_launcher: no worker command given")
    extra = dict(kv.split("=", 1) for kv in ns.env)
    sched_host = ns.scheduler_host or (servers[0] if servers else workers[0])

    launch = [ns.remote_python, "-m", "byteps_tpu.launcher.launch", "--"]
    worker_threads: List[threading.Thread] = []
    rcs: Dict[str, int] = {}

    def popen_logged(argv: List[str], tag: str) -> subprocess.Popen:
        os.makedirs(ns.log_dir, exist_ok=True)
        log = open(f"{ns.log_dir}/{tag}.log", "w")
        return subprocess.Popen(argv, stdout=log, stderr=subprocess.STDOUT)

    # scheduler/server run indefinitely → keep Popen handles so we can tear
    # them down once the workers finish (the reference leaves them running;
    # we do the tidy thing and reap them)
    services: List[subprocess.Popen] = []
    services.append(
        popen_logged(
            ssh_command(
                sched_host,
                build_role_env("scheduler", 0, len(workers), len(servers), sched_host, ns.scheduler_port, extra),
                launch,
            ),
            "scheduler",
        )
    )
    for i, host in enumerate(servers):
        services.append(
            popen_logged(
                ssh_command(
                    host,
                    build_role_env("server", i, len(workers), len(servers), sched_host, ns.scheduler_port, extra),
                    launch,
                ),
                f"server-{i}",
            )
        )

    def run_worker(i: int, host: str) -> None:
        env = build_role_env("worker", i, len(workers), len(servers), sched_host, ns.scheduler_port, extra)
        rcs[f"worker-{i}"] = _run_logged(
            ssh_command(host, env, launch + cmd), f"{ns.log_dir}/worker-{i}.log"
        )

    for i, host in enumerate(workers):
        t = threading.Thread(target=run_worker, args=(i, host), daemon=True)
        t.start()
        worker_threads.append(t)

    # wait for WORKERS only (services never exit on their own)
    for t in worker_threads:
        t.join()
    for p in services:
        p.terminate()
    failed = {k: v for k, v in rcs.items() if v != 0}
    if failed:
        print(f"dist_launcher: failed: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
