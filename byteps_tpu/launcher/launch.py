"""bpslaunch equivalent — per-node process launcher.

Re-design of launcher/launch.py (SURVEY §2.6) for TPU deployments:

- Role from ``DMLC_ROLE`` (worker | server | scheduler | joint), with
  topology either from explicit ``DMLC_*`` env or auto-discovered from TPU
  VM metadata (``discover_tpu_topology``).
- Worker role: the reference spawns one process per GPU
  (launch.py:161-199); a JAX TPU worker is single-process multi-chip, so
  we spawn ONE process per host and export BYTEPS_LOCAL_RANK=0,
  BYTEPS_LOCAL_SIZE=1 — the intra-host axis lives in the device mesh
  instead.  NUMA binding of the host process (the aggregation threads are
  the reference's reason for numactl, launch.py:49-141) is kept via
  ``BYTEPS_VISIBLE_CPU_CORES`` → numactl --physcpubind.
- Server/scheduler roles: exec ``python -m byteps_tpu.server``
  (launch.py:269-277 equivalent).
- ``BYTEPS_ENABLE_GDB=1`` wraps the command in gdb (launch.py:187-192);
  ``BYTEPS_TRACE_ON=1`` pre-creates the trace dir (launch.py:193-197).

Usage:  python -m byteps_tpu.launcher.launch [--] CMD [ARGS...]
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from typing import Dict, List, Optional


REQUIRED_ENV = ["DMLC_ROLE"]
WORKER_REQUIRED_ENV = ["DMLC_NUM_WORKER", "DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT"]


def discover_tpu_topology(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Derive DMLC_* topology from TPU slice metadata when present.

    TPU VMs expose ``TPU_WORKER_ID`` and ``TPU_WORKER_HOSTNAMES``
    (comma-separated) — the launcher maps worker 0's host to the scheduler
    (DMLC_PS_ROOT_URI) and the host count to DMLC_NUM_WORKER, so a plain
    ``bpslaunch python train.py`` works on a pod slice with zero explicit
    config (the reference reads the analogous role info from env set by
    dist_launcher, docs/env.md).
    """
    env = env if env is not None else dict(os.environ)
    out: Dict[str, str] = {}
    hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
    worker_id = env.get("TPU_WORKER_ID", "")
    if hostnames and worker_id != "":
        hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
        out["DMLC_NUM_WORKER"] = str(len(hosts))
        out["DMLC_WORKER_ID"] = str(int(worker_id))
        out.setdefault("DMLC_PS_ROOT_URI", hosts[0])
        out.setdefault("DMLC_PS_ROOT_PORT", "9000")
        out["BYTEPS_GLOBAL_RANK"] = str(int(worker_id))
    return out


def check_env(env: Dict[str, str]) -> None:
    """Validate required topology env (check_env, launch.py:144-158)."""
    missing = [k for k in REQUIRED_ENV if not env.get(k)]
    if env.get("DMLC_ROLE") == "worker" and int(env.get("DMLC_NUM_WORKER", "1")) > 1:
        missing += [k for k in WORKER_REQUIRED_ENV if not env.get(k)]
    if missing:
        raise SystemExit(f"bpslaunch: missing required env: {', '.join(missing)}")


def numa_prefix(env: Dict[str, str]) -> List[str]:
    """numactl binding for the worker's host threads
    (allocate_cpu, launch.py:49-141).  Explicit core list only — the
    per-GPU automatic quota logic has no TPU analogue since there is one
    process per host."""
    cores = env.get("BYTEPS_VISIBLE_CPU_CORES", "")
    if not cores or not shutil.which("numactl"):
        return []
    return ["numactl", f"--physcpubind={cores}"]


def build_worker_command(cmd: List[str], env: Dict[str, str]) -> List[str]:
    full = numa_prefix(env) + cmd
    if env.get("BYTEPS_ENABLE_GDB", "0") == "1":
        full = ["gdb", "-ex", "run", "-ex", "bt", "--batch", "--args"] + full
    return full


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--":
        argv = argv[1:]

    env = dict(os.environ)
    for k, v in discover_tpu_topology(env).items():
        env.setdefault(k, v)
    env.setdefault("DMLC_ROLE", "worker")
    check_env(env)
    role = env["DMLC_ROLE"]

    if env.get("BYTEPS_TRACE_ON", "0") == "1":
        trace_dir = env.get("BYTEPS_TRACE_DIR", ".")
        os.makedirs(os.path.join(trace_dir, env.get("BYTEPS_LOCAL_RANK", "0")), exist_ok=True)

    if role in ("server", "scheduler"):
        # become the server/scheduler process (launch.py:269-277)
        return subprocess.call([sys.executable, "-m", "byteps_tpu.server"], env=env)

    # worker / joint both run the user command
    if not argv:
        raise SystemExit(f"bpslaunch: no command given for {role} role")
    env.setdefault("BYTEPS_LOCAL_RANK", "0")
    env.setdefault("BYTEPS_LOCAL_SIZE", "1")

    if role == "joint":
        # colocated server + worker on one host (mixed mode deployments)
        senv = dict(env, DMLC_ROLE="server")
        server = subprocess.Popen([sys.executable, "-m", "byteps_tpu.server"], env=senv)
        try:
            env["DMLC_ROLE"] = "worker"
            rc = subprocess.call(build_worker_command(argv, env), env=env)
        finally:
            server.terminate()
        return rc

    return subprocess.call(build_worker_command(argv, env), env=env)


if __name__ == "__main__":
    raise SystemExit(main())
