"""bpslaunch equivalent — per-node process launcher.

Re-design of launcher/launch.py (SURVEY §2.6) for TPU deployments:

- Role from ``DMLC_ROLE`` (worker | server | scheduler | joint), with
  topology either from explicit ``DMLC_*`` env or auto-discovered from TPU
  VM metadata (``discover_tpu_topology``).
- Worker role: the reference spawns one process per GPU
  (launch.py:161-199); a JAX TPU worker is single-process multi-chip, so
  we spawn ONE process per host and export BYTEPS_LOCAL_RANK=0,
  BYTEPS_LOCAL_SIZE=1 — the intra-host axis lives in the device mesh
  instead.  NUMA binding of the host process (the aggregation threads are
  the reference's reason for numactl, launch.py:49-141) is kept via
  ``BYTEPS_VISIBLE_CPU_CORES`` → numactl --physcpubind.
- Server/scheduler roles: exec ``python -m byteps_tpu.server``
  (launch.py:269-277 equivalent).
- ``BYTEPS_ENABLE_GDB=1`` wraps the command in gdb (launch.py:187-192);
  ``BYTEPS_TRACE_ON=1`` pre-creates the trace dir (launch.py:193-197).

Usage:  python -m byteps_tpu.launcher.launch [--] CMD [ARGS...]
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import sys
from typing import Dict, List, Optional


REQUIRED_ENV = ["DMLC_ROLE"]
WORKER_REQUIRED_ENV = ["DMLC_NUM_WORKER", "DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT"]
NUMA_PATH = "/sys/devices/system/node"


def get_numa_nodes(
    cpu_mt: bool = True, numa_path: str = NUMA_PATH
) -> List[List[int]]:
    """Per-NUMA-node cpu id lists, e.g. [[0..15], [16..31]].

    With ``cpu_mt`` (BYTEPS_MULTITHREADED_CPU, default on) only the first
    half of each node — the physical cores — is planned; hyperthread
    siblings are re-added per allocation (launch.py:50-72)."""
    nodes: List[List[int]] = []
    if not os.path.isdir(numa_path):
        return nodes
    for entry in sorted(os.listdir(numa_path)):
        if not re.fullmatch(r"node\d+", entry):
            continue
        cpu_ids = sorted(
            int(m.group(1))
            for item in os.listdir(os.path.join(numa_path, entry))
            if (m := re.fullmatch(r"cpu(\d+)", item))
        )
        if not cpu_ids:
            continue
        if cpu_mt:
            cpu_ids = cpu_ids[: len(cpu_ids) // 2]
        nodes.append(cpu_ids)
    return nodes


def allocate_cpu(
    local_size: int,
    env: Optional[Dict[str, str]] = None,
    nodes: Optional[List[List[int]]] = None,
) -> Optional[List[List[int]]]:
    """Automatic per-process core quotas (allocate_cpu, launch.py:49-141).

    The LAST local process is the root (it runs the aggregation/PS-facing
    threads) and gets every core the others left — the reference gives the
    root more cpu for the same reason.  Knobs honored:
    ``BYTEPS_NUMA_DEFAULT_QUOTA``, ``BYTEPS_NUMA_ROOT_QUOTA``,
    ``BYTEPS_CPU_BLACKLIST``, ``BYTEPS_MULTITHREADED_CPU``.

    Returns one core list per local rank (hyperthread siblings included
    when cpu_mt), or None when no NUMA information exists.
    """
    env = env if env is not None else dict(os.environ)
    cpu_mt = env.get("BYTEPS_MULTITHREADED_CPU", "1").lower() in ("1", "true")
    if nodes is None:
        nodes = get_numa_nodes(cpu_mt)
    if not nodes or local_size < 1:
        return None
    nodes = [list(n) for n in nodes]
    cpu_num = sum(len(n) for n in nodes)

    default_quota = int(env.get("BYTEPS_NUMA_DEFAULT_QUOTA", cpu_num // local_size))
    while default_quota >= 1 and default_quota * local_size > cpu_num:
        default_quota -= 1
    root_quota = cpu_num - default_quota * (local_size - 1)
    if int(env.get("BYTEPS_NUMA_ROOT_QUOTA", "0")):
        root_quota = int(env["BYTEPS_NUMA_ROOT_QUOTA"])  # explicit wins, unclamped
    elif local_size > 1:
        # sharing the host: keep the root NUMA-local like the reference;
        # a SINGLE process per host (the TPU default) gets every core
        node_size = len(nodes[0])
        while root_quota > node_size >= 1:
            root_quota -= 1

    blacklist = {
        int(c) for c in env.get("BYTEPS_CPU_BLACKLIST", "-1").split(",") if c
    }
    # hyperthread sibling offset: cpu i pairs with i + physical-core count
    sibling_off = cpu_num

    out: List[List[int]] = []
    for quota in [default_quota] * (local_size - 1) + [root_quota]:
        taken: List[int] = []
        q = max(1, quota)
        while q > 0:
            # prefer one NUMA node that satisfies the remaining quota
            # whole; otherwise drain the largest node and keep filling
            # from the next (multi-socket quotas span nodes)
            node = next((n for n in nodes if len(n) >= q), None)
            if node is None:
                node = max(nodes, key=len, default=None)
                if not node:
                    break
            grab = min(q, len(node))
            taken.extend(node[:grab])
            node[:] = node[grab:]
            q -= grab
        alloc = [c for c in taken if c not in blacklist]
        if cpu_mt:
            alloc.extend(
                c + sibling_off for c in taken if c + sibling_off not in blacklist
            )
        out.append(alloc)
    return out


def discover_tpu_topology(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Derive DMLC_* topology from TPU slice metadata when present.

    TPU VMs expose ``TPU_WORKER_ID`` and ``TPU_WORKER_HOSTNAMES``
    (comma-separated) — the launcher maps worker 0's host to the scheduler
    (DMLC_PS_ROOT_URI) and the host count to DMLC_NUM_WORKER, so a plain
    ``bpslaunch python train.py`` works on a pod slice with zero explicit
    config (the reference reads the analogous role info from env set by
    dist_launcher, docs/env.md).
    """
    env = env if env is not None else dict(os.environ)
    out: Dict[str, str] = {}
    hostnames = env.get("TPU_WORKER_HOSTNAMES", "")
    worker_id = env.get("TPU_WORKER_ID", "")
    if hostnames and worker_id != "":
        hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
        out["DMLC_NUM_WORKER"] = str(len(hosts))
        out["DMLC_WORKER_ID"] = str(int(worker_id))
        out.setdefault("DMLC_PS_ROOT_URI", hosts[0])
        out.setdefault("DMLC_PS_ROOT_PORT", "9000")
        out["BYTEPS_GLOBAL_RANK"] = str(int(worker_id))
    return out


def check_env(env: Dict[str, str]) -> None:
    """Validate required topology env (check_env, launch.py:144-158)."""
    missing = [k for k in REQUIRED_ENV if not env.get(k)]
    if env.get("DMLC_ROLE") == "worker" and int(env.get("DMLC_NUM_WORKER", "1")) > 1:
        missing += [k for k in WORKER_REQUIRED_ENV if not env.get(k)]
    if missing:
        raise SystemExit(f"bpslaunch: missing required env: {', '.join(missing)}")


def numa_prefix(env: Dict[str, str]) -> List[str]:
    """numactl binding for the worker's host threads (allocate_cpu,
    launch.py:49-141): explicit ``BYTEPS_VISIBLE_CPU_CORES`` wins; with
    ``BYTEPS_NUMA_ON`` (default 1) and NUMA info present, the automatic
    quota plan binds this local rank's share."""
    if not shutil.which("numactl"):
        return []
    cores = env.get("BYTEPS_VISIBLE_CPU_CORES", "")
    if not cores and env.get("BYTEPS_NUMA_ON", "1") == "1":
        local_size = int(env.get("BYTEPS_LOCAL_SIZE", "1"))
        local_rank = int(env.get("BYTEPS_LOCAL_RANK", "0"))
        plan = allocate_cpu(local_size, env)
        if plan and local_rank < len(plan) and plan[local_rank]:
            cores = ",".join(str(c) for c in plan[local_rank])
    if not cores:
        return []
    return ["numactl", f"--physcpubind={cores}"]


def build_worker_command(cmd: List[str], env: Dict[str, str]) -> List[str]:
    full = numa_prefix(env) + cmd
    if env.get("BYTEPS_ENABLE_GDB", "0") == "1":
        full = ["gdb", "-ex", "run", "-ex", "bt", "--batch", "--args"] + full
    return full


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "--":
        argv = argv[1:]

    env = dict(os.environ)
    for k, v in discover_tpu_topology(env).items():
        env.setdefault(k, v)
    env.setdefault("DMLC_ROLE", "worker")
    check_env(env)
    role = env["DMLC_ROLE"]

    if env.get("BYTEPS_TRACE_ON", "0") == "1":
        trace_dir = env.get("BYTEPS_TRACE_DIR", ".")
        os.makedirs(os.path.join(trace_dir, env.get("BYTEPS_LOCAL_RANK", "0")), exist_ok=True)

    if role in ("server", "scheduler"):
        # become the server/scheduler process (launch.py:269-277)
        return subprocess.call([sys.executable, "-m", "byteps_tpu.server"], env=env)

    # worker / joint both run the user command
    if not argv:
        raise SystemExit(f"bpslaunch: no command given for {role} role")
    env.setdefault("BYTEPS_LOCAL_RANK", "0")
    env.setdefault("BYTEPS_LOCAL_SIZE", "1")

    if role == "joint":
        # colocated server + worker on one host (mixed mode deployments)
        senv = dict(env, DMLC_ROLE="server")
        server = subprocess.Popen([sys.executable, "-m", "byteps_tpu.server"], env=senv)
        try:
            env["DMLC_ROLE"] = "worker"
            rc = subprocess.call(build_worker_command(argv, env), env=env)
        finally:
            server.terminate()
        return rc

    return subprocess.call(build_worker_command(argv, env), env=env)


if __name__ == "__main__":
    raise SystemExit(main())
