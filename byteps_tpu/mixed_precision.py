"""Mixed-precision training with master weights and dynamic loss scaling.

Parity with the reference's ``_HalfPrecisionDistributedOptimizer``
(misc/imagenet18/__init__.py:39+): fp16/bf16 compute with fp32 master
weights and a loss scale.  TPU-native shape: an optax gradient
transformation pair —

- :func:`dynamic_loss_scale` — scales the loss up before backward, checks
  grads for inf/nan, unscales, halves the scale on overflow (skipping the
  step) and doubles it every ``growth_interval`` clean steps;
- :func:`master_weights` — keeps fp32 optimizer state for bf16/f16 params.

On TPU the usual practice is bf16-compute + fp32-params (no loss scale
needed thanks to bf16's exponent range); the dynamic scaler is provided for
fp16 parity and for extremely deep models.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax


class LossScaleState(NamedTuple):
    scale: jax.Array  # current loss scale
    good_steps: jax.Array  # consecutive non-overflow steps
    inner: Any


def dynamic_loss_scale(
    inner: optax.GradientTransformation,
    init_scale: float = 2.0**15,
    growth_interval: int = 2000,
    factor: float = 2.0,
) -> optax.GradientTransformation:
    """Wrap an optimizer with dynamic loss scaling.

    The caller multiplies its loss by ``state.scale`` before taking grads
    (or equivalently multiplies grads; both are supported since we unscale
    here).  On overflow the update is zeroed (step skipped) and the scale
    halves; after ``growth_interval`` clean steps it doubles.
    """

    def init_fn(params):
        return LossScaleState(
            scale=jnp.asarray(init_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            inner=inner.init(params),
        )

    def update_fn(updates, state, params=None):
        inv = 1.0 / state.scale
        unscaled = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv), updates
        )
        finite = jnp.all(
            jnp.stack(
                [jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(unscaled)]
            )
        )
        new_updates, new_inner = inner.update(unscaled, state.inner, params)
        # skipped step: zero updates, keep inner state
        zero_updates = jax.tree_util.tree_map(jnp.zeros_like, new_updates)
        updates_out = jax.tree_util.tree_map(
            lambda u, z: jnp.where(finite, u, z), new_updates, zero_updates
        )
        inner_out = jax.tree_util.tree_map(
            lambda n, o: jnp.where(finite, n, o) if isinstance(n, jax.Array) and n.shape == o.shape else n,
            new_inner, state.inner,
        )
        good = jnp.where(finite, state.good_steps + 1, 0)
        grow = good >= growth_interval
        scale = jnp.where(
            finite,
            jnp.where(grow, state.scale * factor, state.scale),
            jnp.maximum(state.scale / factor, 1.0),
        )
        good = jnp.where(grow, 0, good)
        return updates_out, LossScaleState(scale=scale, good_steps=good, inner=inner_out)

    return optax.GradientTransformation(init_fn, update_fn)


def master_weights(
    inner: optax.GradientTransformation,
    compute_dtype: Any = jnp.bfloat16,
) -> optax.GradientTransformation:
    """Keep fp32 master copies for low-precision parameters: gradients are
    upcast, the inner optimizer runs in fp32 on the masters, and updates
    are emitted in the parameter dtype (the reference's master-weight loop,
    misc/imagenet18/__init__.py:80-140)."""

    class MasterState(NamedTuple):
        masters: Any
        inner: Any

    def init_fn(params):
        masters = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params
        )
        return MasterState(masters=masters, inner=inner.init(masters))

    def update_fn(updates, state, params=None):
        grads32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), updates)
        upd32, new_inner = inner.update(grads32, state.inner, state.masters)
        new_masters = optax.apply_updates(state.masters, upd32)
        # emitted update = newly-cast params minus old params, in param dtype
        def emit(m_new, p):
            return (m_new.astype(p.dtype) - p).astype(p.dtype)

        out = jax.tree_util.tree_map(emit, new_masters, params)
        return out, MasterState(masters=new_masters, inner=new_inner)

    return optax.GradientTransformation(init_fn, update_fn)
