"""Model zoo.

The reference keeps models in examples (example/{pytorch,tensorflow,mxnet},
SURVEY §2.7); here the flagship transformer family (BERT-large, GPT-2) is a
first-class, fully-shardable implementation, plus conv nets (ResNet-50,
VGG-16) matching the reference's benchmark configs (BASELINE.md).
"""

from byteps_tpu.models.transformer import (
    TransformerConfig,
    bert_large,
    gpt2_medium,
    init_params,
    build_train_step,
    build_forward,
)
