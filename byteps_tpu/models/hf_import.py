"""HuggingFace checkpoint import for the flagship transformer.

Converts a ``transformers`` GPT-2 model's weights into the flat stacked
param dict of :mod:`byteps_tpu.models.transformer`, giving checkpoint
interoperability (load a pretrained torch GPT-2, continue training
TPU-native with full 4-D parallelism) and an architecture cross-check:
our logits must match HF's bit-for-bit up to float tolerance.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from byteps_tpu.models.transformer import TransformerConfig


def config_from_gpt2(hf_config) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.n_embd,
        n_heads=hf_config.n_head,
        d_head=hf_config.n_embd // hf_config.n_head,
        d_ff=hf_config.n_inner or 4 * hf_config.n_embd,
        n_layers=hf_config.n_layer,
        max_seq=hf_config.n_positions,
        causal=True,
        attn_bias=True,
        remat=False,
    )


def load_gpt2_weights(hf_model, pp_size: int = 1) -> Tuple[TransformerConfig, Dict[str, np.ndarray]]:
    """GPT2LMHeadModel → (config, params).  Layer params stacked with
    leading dims (pp, layers_per_stage)."""
    cfg = config_from_gpt2(hf_model.config)
    D, H, dh, F, L = cfg.d_model, cfg.n_heads, cfg.d_head, cfg.d_ff, cfg.n_layers
    if L % pp_size:
        raise ValueError(f"n_layers {L} not divisible by pp {pp_size}")
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}

    def stack(fn):
        per_layer = [fn(i) for i in range(L)]
        arr = np.stack(per_layer)  # (L, ...)
        return arr.reshape((pp_size, L // pp_size) + arr.shape[1:])

    params: Dict[str, np.ndarray] = {
        "embed": sd["transformer.wte.weight"].astype(np.float32),
        "pos": sd["transformer.wpe.weight"].astype(np.float32),
        "ln_f_s": sd["transformer.ln_f.weight"].astype(np.float32),
        "ln_f_b": sd["transformer.ln_f.bias"].astype(np.float32),
        # GPT-2 ties the LM head to the token embedding
        "head": sd["transformer.wte.weight"].T.astype(np.float32),
        "ln1_s": stack(lambda i: sd[f"transformer.h.{i}.ln_1.weight"]),
        "ln1_b": stack(lambda i: sd[f"transformer.h.{i}.ln_1.bias"]),
        "ln2_s": stack(lambda i: sd[f"transformer.h.{i}.ln_2.weight"]),
        "ln2_b": stack(lambda i: sd[f"transformer.h.{i}.ln_2.bias"]),
    }

    # c_attn is HF Conv1D: weight (D, 3D) applied as x @ W + b
    def qkv(i, which):
        w = sd[f"transformer.h.{i}.attn.c_attn.weight"]  # (D, 3D)
        part = np.split(w, 3, axis=1)[which]  # (D, D)
        return part.reshape(D, H, dh)

    def qkv_b(i, which):
        b = sd[f"transformer.h.{i}.attn.c_attn.bias"]  # (3D,)
        return np.split(b, 3)[which].reshape(H, dh)

    params["wq"] = stack(lambda i: qkv(i, 0)).astype(np.float32)
    params["wk"] = stack(lambda i: qkv(i, 1)).astype(np.float32)
    params["wv"] = stack(lambda i: qkv(i, 2)).astype(np.float32)
    params["wq_b"] = stack(lambda i: qkv_b(i, 0)).astype(np.float32)
    params["wk_b"] = stack(lambda i: qkv_b(i, 1)).astype(np.float32)
    params["wv_b"] = stack(lambda i: qkv_b(i, 2)).astype(np.float32)
    params["wo"] = stack(
        lambda i: sd[f"transformer.h.{i}.attn.c_proj.weight"].reshape(H, dh, D)
    ).astype(np.float32)
    params["wo_b"] = stack(
        lambda i: sd[f"transformer.h.{i}.attn.c_proj.bias"]
    ).astype(np.float32)
    params["w1"] = stack(lambda i: sd[f"transformer.h.{i}.mlp.c_fc.weight"]).astype(np.float32)
    params["b1"] = stack(lambda i: sd[f"transformer.h.{i}.mlp.c_fc.bias"]).astype(np.float32)
    params["w2"] = stack(lambda i: sd[f"transformer.h.{i}.mlp.c_proj.weight"]).astype(np.float32)
    params["b2"] = stack(lambda i: sd[f"transformer.h.{i}.mlp.c_proj.bias"]).astype(np.float32)
    return cfg, params
