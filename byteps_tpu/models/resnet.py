"""ResNet family (flax) — the reference's throughput benchmark model
(docs/performance.md:3-12: ResNet-50, batch 64/device).

TPU notes: NHWC layout (native for TPU convolutions), bf16 compute with
fp32 batch-norm statistics, SAME padding so spatial dims stay MXU-tileable.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic two-conv block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1-3x3-1x1 bottleneck (ResNet-50/101/152)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME")
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
        )
        act = nn.relu
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x.astype(self.dtype))
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i, conv=conv, norm=norm, act=act,
                    strides=strides,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
# tiny variant for CPU-mesh tests
ResNetTiny = partial(
    ResNet, stage_sizes=[1, 1], block_cls=ResNetBlock, num_filters=8, num_classes=10
)
