"""Flagship transformer family — fully shardable over (dp, pp, sp, tp).

TPU-first design, not a port: the whole train step is ONE compiled SPMD
program under ``shard_map`` over a 4-D mesh:

    dp — batch sharding; gradients psum over ICI (the reference's entire
         data-parallel capability, SURVEY §2.7)
    pp — pipeline stages: layer stack sharded on the leading stage dim,
         GPipe-style microbatch schedule driven by lax.scan with
         lax.ppermute hops between stages
    sp — sequence/context parallelism: ring attention
         (byteps_tpu.parallel.ring_attention) rotating KV blocks on ICI;
         doubles as the expert-parallel axis for MoE (DeepSpeed-MoE
         grouping)
    tp — megatron-style tensor parallelism: attention heads and MLP hidden
         column-sharded, row-parallel matmuls psum'd

Parameters are stored as a flat dict of stacked global arrays with leading
dims (pp, layers_per_stage, ...); sharding specs and gradient-sync axes are
derived per entry (a parameter's grads are psum'd over exactly the axes it
is replicated on).

Flagship configs: BERT-large (the reference's headline benchmark,
BASELINE.md) and GPT-2 medium (BASELINE.json config 5).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from byteps_tpu.parallel.moe import moe_aux_loss, moe_mlp
from byteps_tpu.parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 1024
    n_heads: int = 16
    # grouped-query attention: number of K/V heads (None = n_heads, i.e.
    # classic MHA).  Query heads share KV groups of n_heads/n_kv_heads;
    # the decode KV cache stores only n_kv_heads (the GQA memory win)
    n_kv_heads: Optional[int] = None
    d_head: int = 64
    d_ff: int = 4096
    n_layers: int = 24
    max_seq: int = 512
    causal: bool = False  # BERT-style bidirectional by default
    moe: bool = False
    n_experts: int = 8
    # experts per token: 2 = GShard-style with renormalized gates (the
    # quality default), 1 = cheaper Switch-style routing
    moe_top_k: int = 2
    capacity_factor: float = 2.0
    # capacity factor for GENERATION prefill.  None (default) = no-drop
    # serving capacity (cf = n_experts, capacity = token count): prompt
    # tokens are never silently dropped from the MLP and generation output
    # is mesh-independent.  Set a finite value (e.g. the training
    # capacity_factor) to bound prefill memory for very long prompts, at
    # the documented cost of GShard-style per-dp-shard overflow drops.
    prefill_capacity_factor: float | None = None
    moe_aux_coef: float = 0.01
    compute_dtype: Any = jnp.float32
    microbatches: int = 0  # 0 → pipeline stages count
    # rematerialize each transformer layer in backward (jax.checkpoint):
    # trades ~30% more FLOPs for O(layers) less activation memory — the
    # HBM-vs-FLOPs dial the reference cannot turn (it owns no compute graph)
    remat: bool = True
    # use the Pallas flash-attention kernel for the per-device attention
    # when sequence parallelism is off (ring attention otherwise).
    # Default off: measured on TPU v5e, XLA's fused dense attention beats
    # the current Pallas kernel at trainable sequence lengths (seq 128:
    # 412 vs 291 samples/s; seq 1024: 29.4 vs 13.9 on BERT-large) — the
    # kernel is the memory-frugal option for long-context runs where the
    # S^2 score matrix would not fit, not the short-seq fast path.
    use_flash: bool = False
    # sequence-parallel strategy when sp > 1: "ring" (ppermute KV blocks,
    # any head count) or "ulysses" (all-to-all head/seq reshard, needs
    # tp-local heads divisible by sp)
    seq_parallel_impl: str = "ring"

    # qkv/proj bias terms (GPT-2-style checkpoints have them; BERT too)
    attn_bias: bool = False
    # positional encoding: "learned" absolute table (BERT/GPT-2 style) or
    # "rope" rotary embeddings applied to q/k (Llama/GPT-NeoX style —
    # relative, extrapolates past max_seq, composes with ring attention
    # because each key's rotation is baked in before KV blocks travel)
    pos_emb: str = "learned"
    rope_theta: float = 10000.0

    def __post_init__(self):
        if self.seq_parallel_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown seq_parallel_impl {self.seq_parallel_impl!r}; "
                "expected 'ring' or 'ulysses'"
            )
        if self.n_kv_heads is not None and self.n_heads % self.n_kv_heads:
            raise ValueError(
                f"n_heads {self.n_heads} not divisible by n_kv_heads "
                f"{self.n_kv_heads} (query heads share KV groups evenly)"
            )
        if self.pos_emb not in ("learned", "rope"):
            raise ValueError(
                f"unknown pos_emb {self.pos_emb!r}; expected 'learned' or 'rope'"
            )
        if self.pos_emb == "rope" and self.d_head % 2:
            raise ValueError(
                f"rope needs an even d_head, got {self.d_head}"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads


def bert_large(**kw) -> TransformerConfig:
    """BERT-large: 24L, d1024, 16 heads, ff 4096 — the reference's headline
    scaling benchmark (README.md:38-46, BASELINE.md)."""
    return TransformerConfig(
        vocab_size=30528, d_model=1024, n_heads=16, d_head=64, d_ff=4096,
        n_layers=24, causal=False, **kw,
    )


def gpt2_medium(**kw) -> TransformerConfig:
    """GPT-2 medium: 24L, d1024, causal (BASELINE.json config 5)."""
    return TransformerConfig(
        vocab_size=50257, d_model=1024, n_heads=16, d_head=64, d_ff=4096,
        n_layers=24, causal=True, **kw,
    )


def tiny_test(**kw) -> TransformerConfig:
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 16)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_head", 4)
    kw.setdefault("d_ff", 32)
    kw.setdefault("n_layers", 4)
    kw.setdefault("max_seq", 16)
    return TransformerConfig(**kw)


# ---------------------------------------------------------------------------
# Parameters: flat dict of stacked global arrays + per-entry layout table
# ---------------------------------------------------------------------------


def _layouts(cfg: TransformerConfig) -> Dict[str, Tuple]:
    """name → (global_shape_fn(pp, tp, sp) irrelevant — shapes are GLOBAL),
    (partition spec), (grad sync axes).  Spec axes reference the 4-D mesh
    (dp, pp, sp, tp)."""
    D, H, dh, F = cfg.d_model, cfg.n_heads, cfg.d_head, cfg.d_ff
    KV = cfg.kv_heads
    L, V, S, E = cfg.n_layers, cfg.vocab_size, cfg.max_seq, cfg.n_experts
    # leading dims of layer params: (pp, layers_per_stage) — pp filled in
    # at init time when the mesh is known
    table = {
        "embed": ((V, D), P(), ("dp", "pp", "sp", "tp")),
    }
    if cfg.pos_emb == "learned":
        table["pos"] = ((S, D), P(), ("dp", "pp", "sp", "tp"))
    table.update({
        "ln_f_s": ((D,), P(), ("dp", "pp", "sp", "tp")),
        "ln_f_b": ((D,), P(), ("dp", "pp", "sp", "tp")),
        "head": ((D, V), P(), ("dp", "pp", "sp", "tp")),
        # layer-stacked (leading (pp, Lps) added at init)
        "ln1_s": ((D,), P("pp"), ("dp", "sp", "tp")),
        "ln1_b": ((D,), P("pp"), ("dp", "sp", "tp")),
        "ln2_s": ((D,), P("pp"), ("dp", "sp", "tp")),
        "ln2_b": ((D,), P("pp"), ("dp", "sp", "tp")),
        "wq": ((D, H, dh), P("pp", None, None, "tp", None), ("dp", "sp")),
        "wk": ((D, KV, dh), P("pp", None, None, "tp", None), ("dp", "sp")),
        "wv": ((D, KV, dh), P("pp", None, None, "tp", None), ("dp", "sp")),
        "wo": ((H, dh, D), P("pp", None, "tp", None, None), ("dp", "sp")),
    })
    if cfg.attn_bias:
        table.update(
            {
                "wq_b": ((H, dh), P("pp", None, "tp", None), ("dp", "sp")),
                "wk_b": ((KV, dh), P("pp", None, "tp", None), ("dp", "sp")),
                "wv_b": ((KV, dh), P("pp", None, "tp", None), ("dp", "sp")),
                # added after the tp psum, like b2
                "wo_b": ((D,), P("pp"), ("dp", "sp", "tp")),
            }
        )
    if cfg.moe:
        table.update(
            {
                "router": ((D, E), P("pp"), ("dp", "sp", "tp")),
                "ew1": ((E, D, F), P("pp", None, "sp", None, None), ("dp", "tp")),
                "eb1": ((E, F), P("pp", None, "sp", None), ("dp", "tp")),
                "ew2": ((E, F, D), P("pp", None, "sp", None, None), ("dp", "tp")),
                "eb2": ((E, D), P("pp", None, "sp", None), ("dp", "tp")),
            }
        )
    else:
        table.update(
            {
                "w1": ((D, F), P("pp", None, None, "tp"), ("dp", "sp")),
                "b1": ((F,), P("pp", None, "tp"), ("dp", "sp")),
                "w2": ((F, D), P("pp", None, "tp", None), ("dp", "sp")),
                "b2": ((D,), P("pp"), ("dp", "sp", "tp")),
            }
        )
    return table


_LAYER_PARAMS_PREFIXES = (
    "ln1_", "ln2_", "wq", "wk", "wv", "wo", "w1", "b1", "w2", "b2",
    "router", "ew1", "eb1", "ew2", "eb2",
)


def _is_layer_param(name: str) -> bool:
    return any(name.startswith(p) for p in _LAYER_PARAMS_PREFIXES)


def param_specs(cfg: TransformerConfig) -> Dict[str, P]:
    return {k: spec for k, (_, spec, _) in _layouts(cfg).items()}


def grad_sync_axes(cfg: TransformerConfig) -> Dict[str, Tuple[str, ...]]:
    return {k: axes for k, (_, _, axes) in _layouts(cfg).items()}


def init_params(
    cfg: TransformerConfig, seed: int = 0, pp_size: int = 1
) -> Dict[str, np.ndarray]:
    """Host-side init (numpy, float32).  Layer params get leading dims
    (pp, layers_per_stage)."""
    if cfg.n_layers % pp_size:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by pp {pp_size}")
    lps = cfg.n_layers // pp_size
    rng = np.random.default_rng(seed)
    params: Dict[str, np.ndarray] = {}
    for name, (shape, _, _) in _layouts(cfg).items():
        if _is_layer_param(name):
            full = (pp_size, lps) + shape
        else:
            full = shape
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        std = 0.02 if name in ("embed", "pos") else 1.0 / math.sqrt(fan_in)
        if name.endswith("_s"):  # layernorm scales → ones
            arr = np.ones(full, dtype=np.float32)
        elif name.endswith("_b") or name.startswith("b") or name.startswith("eb"):
            arr = np.zeros(full, dtype=np.float32)
        else:
            arr = rng.normal(0.0, std, size=full).astype(np.float32)
        params[name] = arr
    return params


# ---------------------------------------------------------------------------
# Forward pieces (run per-device inside shard_map)
# ---------------------------------------------------------------------------


def _vary_all(x, mesh: Mesh):
    """Mark a value as device-varying over the activation axes (VMA mode).

    Activations vary over dp/sp (data) and pp (stage weights) but stay
    *invariant* over tp: every row-parallel matmul ends in a psum over tp,
    so the residual stream is numerically replicated across tp ranks and
    must be typed accordingly (a psum of a replicated-but-varying-typed
    value would silently multiply by the axis size).

    Scan carries must keep a stable varying-axes type; starting them at the
    full activation type avoids carry mismatches once sharded weights mix in.
    """
    all_axes = tuple(ax for ax in mesh.shape.keys() if ax != "tp")
    if not all_axes:
        return x

    def cast(a):
        try:
            have = set(jax.typeof(a).vma)
        except AttributeError:
            have = set()
        need = tuple(ax for ax in all_axes if ax not in have)
        return lax.pcast(a, need, to="varying") if need else a

    return jax.tree_util.tree_map(cast, x)


def _ln(x, s, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * s + b


def _rope(x, positions, theta: float):
    """Rotary position embedding (rotate-half convention): x (B, H, s, dh)
    rotated per ABSOLUTE position — sequence-parallel ranks and the cached
    decoder pass their global offsets, so rotations stay consistent when
    KV blocks travel the ring or live in the cache."""
    dh = x.shape[-1]
    half = dh // 2
    freqs = jnp.asarray(theta, jnp.float32) ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )
    ang = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # (s, half)
    cos = jnp.cos(ang)[None, None]
    sin = jnp.sin(ang)[None, None]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def _qkv_proj(cfg: TransformerConfig, h, lp, positions=None):
    """Shared QKV projection (tp-local heads: wq (D, H_local, dh)) —
    used by the training stage fn AND the cached decoder so the layer
    math can never diverge between paths.  ``positions``: absolute token
    positions (s,), required when cfg.pos_emb == "rope" (q/k rotated
    in-projection; v untouched)."""
    cdt = cfg.compute_dtype
    q = jnp.einsum("bsd,dhk->bhsk", h, lp["wq"].astype(cdt))
    k = jnp.einsum("bsd,dhk->bhsk", h, lp["wk"].astype(cdt))
    v = jnp.einsum("bsd,dhk->bhsk", h, lp["wv"].astype(cdt))
    if cfg.attn_bias:
        q = q + lp["wq_b"].astype(cdt)[None, :, None, :]
        k = k + lp["wk_b"].astype(cdt)[None, :, None, :]
        v = v + lp["wv_b"].astype(cdt)[None, :, None, :]
    if cfg.pos_emb == "rope":
        assert positions is not None, "rope needs absolute positions"
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _repeat_kv(k, v, n_q_heads: int):
    """Expand grouped K/V heads to the query head count (GQA): each KV
    head serves n_q_heads/kv_heads query heads.  Identity for MHA."""
    rep = n_q_heads // k.shape[1]
    if rep == 1:
        return k, v
    return jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1)


def _attn_out(cfg: TransformerConfig, attn, lp, x):
    """Shared attention output projection + tp row-parallel combine +
    residual."""
    cdt = cfg.compute_dtype
    o = jnp.einsum("bhsk,hkd->bsd", attn, lp["wo"].astype(cdt))
    o = lax.psum(o, "tp")  # row-parallel combine (free at tp=1)
    if cfg.attn_bias:
        o = o + lp["wo_b"].astype(cdt)
    return x + o.astype(x.dtype)


def _dense_mlp(cfg: TransformerConfig, x, lp):
    """Shared dense MLP block (LN → gelu MLP with tp row-parallel combine
    → residual)."""
    cdt = cfg.compute_dtype
    g = _ln(x, lp["ln2_s"], lp["ln2_b"]).astype(cdt)
    hmid = jax.nn.gelu(
        jnp.einsum("bsd,df->bsf", g, lp["w1"].astype(cdt)) + lp["b1"].astype(cdt)
    )
    y = jnp.einsum("bsf,fd->bsd", hmid, lp["w2"].astype(cdt))
    y = lax.psum(y, "tp")  # row-parallel combine
    y = y + lp["b2"].astype(cdt)
    return x + y.astype(x.dtype)


def _moe_block(cfg: TransformerConfig, x, lp, sp: int,
               capacity_factor: float):
    """Shared MoE MLP block (ln2 → routed expert MLP → residual), used by
    the training layer and the cached decoder so the two cannot drift.
    Returns (new residual stream, router input g) — g feeds the aux loss
    so it always matches exactly what was routed."""
    cdt = cfg.compute_dtype
    g = _ln(x, lp["ln2_s"], lp["ln2_b"]).astype(cdt)
    b_, s_, d_ = g.shape
    y = moe_mlp(
        g.reshape(b_ * s_, d_),
        lp["router"].astype(cdt),
        lp["ew1"].astype(cdt), lp["eb1"].astype(cdt),
        lp["ew2"].astype(cdt), lp["eb2"].astype(cdt),
        axis_name="sp" if sp > 1 else None,
        axis_size=sp,
        capacity_factor=capacity_factor,
        top_k=cfg.moe_top_k,
    ).reshape(b_, s_, d_)
    return x + y.astype(x.dtype), g


def _make_stage_fn(cfg: TransformerConfig, mesh: Mesh):
    sp = mesh.shape.get("sp", 1)
    tp = mesh.shape.get("tp", 1)
    cdt = cfg.compute_dtype

    def layer_fn(x, lp):
        # x: (B, S_local, D)
        h = _ln(x, lp["ln1_s"], lp["ln1_b"]).astype(cdt)
        s_local = x.shape[1]
        positions = (
            lax.axis_index("sp") * s_local + jnp.arange(s_local)
            if cfg.pos_emb == "rope" else None
        )
        q, k, v = _qkv_proj(cfg, h, lp, positions)
        k, v = _repeat_kv(k, v, q.shape[1])  # GQA: groups -> query heads
        if sp == 1 and cfg.use_flash:
            from byteps_tpu.ops.flash_attention import flash_attention

            attn = flash_attention(q, k, v, causal=cfg.causal)
        elif sp > 1 and cfg.seq_parallel_impl == "ulysses":
            from byteps_tpu.parallel.ulysses import ulysses_attention

            attn = ulysses_attention(
                q, k, v, axis_name="sp", axis_size=sp, causal=cfg.causal
            )
        elif sp > 1 and cfg.use_flash:
            # long-context composition (round-2 VERDICT #9): flash-kernel
            # hops inside the ring — O(block) memory per hop instead of
            # the (B, H, S_local, S_local) per-hop score matrix
            from byteps_tpu.parallel.ring_attention import ring_flash_attention

            attn = ring_flash_attention(
                q, k, v, axis_name="sp", axis_size=sp, causal=cfg.causal
            )
        else:
            attn = ring_attention(
                q, k, v, axis_name="sp" if sp > 1 else None, axis_size=sp,
                causal=cfg.causal,
            )
        x = _attn_out(cfg, attn, lp, x)

        if cfg.moe:
            x, g = _moe_block(cfg, x, lp, sp, cfg.capacity_factor)
            b_, s_, d_ = g.shape
            aux = moe_aux_loss(
                g.reshape(b_ * s_, d_), lp["router"].astype(cdt), sp,
                lp["ew1"].shape[0],
            )
        else:
            x = _dense_mlp(cfg, x, lp)
            aux = jnp.zeros((), cdt)
        return x, aux

    def stage_fn(stage_params: Dict[str, jax.Array], x: jax.Array):
        """Run this pp rank's layer stack via scan; stage_params leaves have
        leading dim layers_per_stage."""
        body_fn = layer_fn
        if cfg.remat:
            body_fn = jax.checkpoint(
                layer_fn, policy=jax.checkpoint_policies.nothing_saveable
            )

        def body(carry, lp):
            y, aux = body_fn(carry, lp)
            return y, aux

        x, auxs = lax.scan(body, x, stage_params)
        return x, jnp.sum(auxs)

    return stage_fn


def _pipeline(cfg: TransformerConfig, mesh: Mesh, stage_fn, stage_params, x_mb):
    """GPipe-style pipelined forward under shard_map.

    x_mb: (M, Bmb, S_local, D) embedded microbatches (meaningful on every
    rank; only stage 0 consumes them).  Returns (M, Bmb, S_local, D) final
    activations (meaningful on the last stage) and the masked MoE aux sum.

    The schedule runs M + pp - 1 ticks; each tick every stage processes its
    current microbatch and ppermutes the activation downstream.  Bubble
    ticks compute garbage that is masked out of outputs and aux.
    """
    pp = mesh.shape.get("pp", 1)
    if pp == 1:
        def body(carry, x):
            y, aux = stage_fn(stage_params, x)
            return carry + aux, y
        aux0 = _vary_all(jnp.zeros((), cfg.compute_dtype), mesh)
        aux, ys = lax.scan(body, aux0, x_mb)
        return ys, aux

    idx = lax.axis_index("pp")
    m = x_mb.shape[0]
    ticks = m + pp - 1
    perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        buf, outputs, aux_acc = carry
        mb = jnp.clip(t - idx, 0, m - 1)
        x_in = jnp.where(idx == 0, lax.dynamic_index_in_dim(x_mb, mb, 0, keepdims=False), buf)
        y, aux = stage_fn(stage_params, x_in)
        valid = jnp.logical_and(t - idx >= 0, t - idx < m)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        is_last = idx == pp - 1
        write = jnp.logical_and(valid, is_last)
        prev = lax.dynamic_index_in_dim(outputs, mb, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, y, prev), mb, 0
        )
        buf_next = lax.ppermute(y, "pp", perm)
        return (buf_next, outputs, aux_acc), None

    buf0 = _vary_all(jnp.zeros_like(x_mb[0]), mesh)
    out0 = _vary_all(jnp.zeros_like(x_mb), mesh)
    aux0 = _vary_all(jnp.zeros((), cfg.compute_dtype), mesh)
    (_, outputs, aux), _ = lax.scan(tick, (buf0, out0, aux0), jnp.arange(ticks))
    return outputs, aux


def _local_forward(cfg: TransformerConfig, mesh: Mesh, params, tokens):
    """Per-device forward body: embed → pipeline → final-LN → logits.

    tokens: (B_local, S_local) int32.  Returns ((M, Bmb, S_local, V) logits,
    aux) — logits meaningful on the last pp stage.
    """
    pp = mesh.shape.get("pp", 1)
    sp = mesh.shape.get("sp", 1)
    stage_fn = _make_stage_fn(cfg, mesh)

    # squeeze the pp-shard dim off layer params: (1, Lps, ...) → (Lps, ...)
    stage_params = {
        k: v[0] for k, v in params.items() if _is_layer_param(k)
    }

    b_local, s_local = tokens.shape
    sp_idx = lax.axis_index("sp")
    x = params["embed"][tokens]
    if cfg.pos_emb == "learned":
        positions = sp_idx * s_local + jnp.arange(s_local)
        x = x + params["pos"][positions]
    x = _vary_all(x.astype(cfg.compute_dtype), mesh)

    m = cfg.microbatches or pp
    if b_local % m:
        raise ValueError(f"local batch {b_local} not divisible by {m} microbatches")
    x_mb = x.reshape(m, b_local // m, s_local, cfg.d_model)

    outputs, aux = _pipeline(cfg, mesh, stage_fn, stage_params, x_mb)
    h = _ln(outputs, params["ln_f_s"], params["ln_f_b"]).astype(cfg.compute_dtype)
    logits = jnp.einsum("mbsd,dv->mbsv", h, params["head"].astype(cfg.compute_dtype))
    return logits, aux


def _local_loss(cfg: TransformerConfig, mesh: Mesh, params, tokens, targets):
    """Global mean token cross-entropy, identical on every rank after psums.

    Positions with ``target < 0`` are ignored — that one convention covers
    BERT-style masked-LM pretraining (loss only on masked positions; the
    reference's headline benchmark is exactly this workload) and padding.
    """
    pp = mesh.shape.get("pp", 1)
    logits, aux = _local_forward(cfg, mesh, params, tokens)
    m = logits.shape[0]
    tgt = targets.reshape(m, -1, targets.shape[-1])
    valid = (tgt >= 0).astype(jnp.float32)
    safe_tgt = jnp.maximum(tgt, 0)
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    gold = jnp.take_along_axis(
        logits.astype(jnp.float32), safe_tgt[..., None], axis=-1
    )[..., 0]
    token_loss = (logz - gold) * valid  # (M, Bmb, S_local)
    local_sum = jnp.sum(token_loss)
    local_cnt = jnp.sum(valid)
    # only the last stage holds real logits; the pp-psum picks its value
    # (free no-ops at axis size 1, and they make the loss VMA-invariant
    # over every mesh axis so it is truly replicated)
    is_last = lax.axis_index("pp") == pp - 1
    local_sum = jnp.where(is_last, local_sum, 0.0)
    local_cnt = jnp.where(is_last, local_cnt, 0.0)
    for ax in ("pp", "dp", "sp"):
        local_sum = lax.psum(local_sum, ax)
        local_cnt = lax.psum(local_cnt, ax)
        aux = lax.psum(aux, ax)
    loss = local_sum / local_cnt
    if cfg.moe:
        loss = loss + cfg.moe_aux_coef * aux.astype(jnp.float32)
    return loss


# ---------------------------------------------------------------------------
# Public builders
# ---------------------------------------------------------------------------


def validate_mesh(cfg: TransformerConfig, mesh: Mesh) -> None:
    """Config×mesh checks that can only run once the mesh is known.

    wq is tp-sharded on the query-head dim and wk/wv on the KV-head dim,
    so both head counts must divide tp — otherwise the failure surfaces
    later as an opaque shard_map/NamedSharding error instead of naming
    the bad config (ADVICE r4)."""
    tp = mesh.shape.get("tp", 1)
    if cfg.n_heads % tp:
        raise ValueError(
            f"n_heads {cfg.n_heads} not divisible by tp={tp}: wq is "
            "tp-sharded on the head dim"
        )
    if cfg.kv_heads % tp:
        raise ValueError(
            f"n_kv_heads {cfg.kv_heads} not divisible by tp={tp}: wk/wv "
            "are tp-sharded on the KV-head dim — use more KV heads or a "
            "smaller tp axis (GQA groups cannot span tp shards)"
        )


def shard_params(params: Dict[str, np.ndarray], cfg: TransformerConfig, mesh: Mesh):
    """device_put the host params with their NamedShardings."""
    validate_mesh(cfg, mesh)
    specs = param_specs(cfg)
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


def build_forward(cfg: TransformerConfig, mesh: Mesh) -> Callable:
    """Jitted SPMD forward: (params, tokens) → logits (M, Bmb, S_local, V).

    Single-chip friendly: with a 1-device mesh all collectives degenerate.
    """
    validate_mesh(cfg, mesh)
    specs = param_specs(cfg)
    pp = mesh.shape.get("pp", 1)

    def fwd(params, tokens):
        logits, _ = _local_forward(cfg, mesh, params, tokens)
        # select the last pipeline stage's logits (garbage elsewhere)
        is_last = lax.axis_index("pp") == pp - 1
        logits = lax.psum(jnp.where(is_last, logits, 0.0), "pp")
        return logits

    shmapped = jax.shard_map(
        fwd,
        mesh=mesh,
        in_specs=(specs, P("dp", "sp")),
        out_specs=P(None, "dp", "sp", None),
        check_vma=True,
    )
    return jax.jit(shmapped)


def build_generate(cfg: TransformerConfig, mesh: Mesh) -> Callable:
    """Greedy decoding: ``generate(params, prompt, n_new) → (B, S0+n_new)``.

    Recompute-based (no KV cache yet): each step runs the cached jitted
    forward on the fixed ``max_seq`` window — causal masking makes the
    right-padding inert.  Requires ``cfg.causal``.
    """
    if not cfg.causal:
        raise ValueError("generation requires a causal config")
    fwd = build_forward(cfg, mesh)

    def generate(params, prompt: np.ndarray, n_new: int) -> np.ndarray:
        prompt = np.asarray(prompt, dtype=np.int32)
        b, s0 = prompt.shape
        if s0 + n_new > cfg.max_seq:
            raise ValueError(f"{s0}+{n_new} exceeds max_seq {cfg.max_seq}")
        dp = mesh.shape.get("dp", 1)
        if b % dp:
            raise ValueError(f"batch {b} not divisible by dp={dp}")
        buf = np.zeros((b, cfg.max_seq), dtype=np.int32)
        buf[:, :s0] = prompt
        for i in range(s0, s0 + n_new):
            logits = fwd(params, jnp.asarray(buf))  # (M, dp*Bmb, S, V)
            arr = np.asarray(logits)
            m, g, s, v = arr.shape
            # Undo the assembly permutation: dim 1 is dp-shard-major while
            # input rows are dp-major with each shard's rows split across
            # the M microbatches — (M, dp, Bmb) must come back together as
            # (dp, M, Bmb) to restore input batch order.
            step_logits = (
                arr.reshape(m, dp, g // dp, s, v)
                .transpose(1, 0, 2, 3, 4)
                .reshape(-1, s, v)
            )
            buf[:, i] = step_logits[:, i - 1, :].argmax(-1)
        return buf[:, : s0 + n_new]

    return generate


def build_generate_cached(cfg: TransformerConfig, mesh: Mesh) -> Callable:
    """KV-cached greedy decoding — the TPU-first generation path.

    Unlike :func:`build_generate` (recompute per token), this keeps per-
    layer K/V caches in HBM and runs the WHOLE decode as one compiled
    ``lax.scan``: prefill writes the prompt's K/V in a single batched
    pass, then each scan step embeds one token, attends against the cache
    (static ``max_seq`` shapes — XLA-friendly), appends its K/V, and emits
    the argmax.  O(S) attention per new token instead of O(S²) recompute.

    Supported mesh axes: dp (batch), tp (heads), pp (layer stages: each
    token's forward hops stage→stage via ppermute, the decode-inherent
    pipeline bubble), and sp (replicated residual stream — sequence
    parallelism has no per-token decode role; for MoE configs sp doubles
    as the EXPERT axis, with the all_to_all dispatch running on the
    replicated tokens).  Requires a causal config.
    """
    if not cfg.causal:
        raise ValueError("generation requires a causal config")

    cdt = cfg.compute_dtype
    S_max = cfg.max_seq
    pp = mesh.shape.get("pp", 1)
    sp = mesh.shape.get("sp", 1)

    def cached_layer(x, lp, kc, vc, offset, cf):
        """x: (B, s, D); kc/vc: (B, H_local, S_max, dh); returns updated
        residual stream and caches with positions [offset, offset+s).
        Projections and MLP are the SAME helpers the training stage uses —
        only the attention core (cache append + masked full-cache attend)
        differs."""
        s = x.shape[1]
        h = _ln(x, lp["ln1_s"], lp["ln1_b"]).astype(cdt)
        positions = (
            offset + jnp.arange(s) if cfg.pos_emb == "rope" else None
        )
        q, k, v = _qkv_proj(cfg, h, lp, positions)
        # the cache holds KV heads only (the GQA decode-memory win); the
        # attend below groups query heads over it without materializing
        # a repeated cache
        kc = lax.dynamic_update_slice_in_dim(kc, k.astype(kc.dtype), offset, axis=2)
        vc = lax.dynamic_update_slice_in_dim(vc, v.astype(vc.dtype), offset, axis=2)
        bq, hq = q.shape[0], q.shape[1]
        hkv = kc.shape[1]
        rep = hq // hkv
        qg = q.reshape(bq, hkv, rep, s, cfg.d_head)
        scores = jnp.einsum("bgrsk,bgtk->bgrst", qg, kc.astype(cdt))
        scores = scores / np.sqrt(cfg.d_head).astype(cdt)
        # query i (absolute offset+i) may see cache positions t <= offset+i
        t_idx = jnp.arange(S_max)
        i_idx = offset + jnp.arange(s)
        mask = t_idx[None, :] <= i_idx[:, None]  # (s, S_max)
        scores = jnp.where(
            mask[None, None, None], scores, jnp.asarray(-1e30, cdt)
        )
        attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cdt)
        ctx = jnp.einsum("bgrst,bgtk->bgrsk", attn, vc.astype(cdt))
        ctx = ctx.reshape(bq, hq, s, cfg.d_head)
        x = _attn_out(cfg, ctx, lp, x)
        if cfg.moe:
            # expert-parallel MLP: decode tokens are REPLICATED across the
            # sp (expert) axis, and the all_to_all dispatch/inverse is
            # copy-symmetric — every rank reassembles the full expert
            # output, so the replicated-token result stays identical on
            # all sp members (n redundant capacity copies, trivial at
            # decode token counts).  ``cf`` is the capacity factor:
            # no-drop serving capacity (cf = n_experts ⇒ capacity = t)
            # for the per-token steps AND, by default, for prefill
            # (cfg.prefill_capacity_factor opts back into memory-bounded
            # training semantics for very long prompts).
            y, _ = _moe_block(cfg, x, lp, sp, cf)
            return y, kc, vc
        return _dense_mlp(cfg, x, lp), kc, vc

    def run_layers(stage_params, x, kcs, vcs, offset, cf):
        """scan the layer stack; kcs/vcs leading dim = layers."""

        def body(carry, inp):
            xc = carry
            lp, kc, vc = inp
            xc, kc, vc = cached_layer(xc, lp, kc, vc, offset, cf)
            return xc, (kc, vc)

        x, (kcs, vcs) = lax.scan(body, x, (stage_params, kcs, vcs))
        return x, kcs, vcs

    def full_stack(stage_params, x, kcs, vcs, offset, cf):
        """Run the FULL model depth.  With pp == 1 that is just the local
        stack; otherwise unrolled pp turns: at turn s only stage s runs its
        local layers (lax.cond keeps the others idle — the decode-inherent
        pipeline bubble), then the residual hops to stage s+1 via ppermute.
        The last stage's output is psum-broadcast so every stage computes
        the same logits/token (head params are replicated over pp)."""
        if pp == 1:
            return run_layers(stage_params, x, kcs, vcs, offset, cf)
        pp_idx = lax.axis_index("pp")

        def mine(ops):
            xx, kk, vv = ops
            return run_layers(stage_params, xx, kk, vv, offset, cf)

        for turn in range(pp):
            x, kcs, vcs = lax.cond(
                pp_idx == turn, mine, lambda ops: ops, (x, kcs, vcs)
            )
            if turn != pp - 1:
                x = lax.ppermute(
                    x, "pp", [(j, (j + 1) % pp) for j in range(pp)]
                )
        x = lax.psum(jnp.where(pp_idx == pp - 1, x, jnp.zeros_like(x)), "pp")
        return x, kcs, vcs

    def logits_of(params, x):
        h = _ln(x, params["ln_f_s"], params["ln_f_b"]).astype(cdt)
        return jnp.einsum("bsd,dv->bsv", h, params["head"].astype(cdt))

    def gen_fn(params, tokens, temperature, key, n_new: int,
               sampling: bool = False, top_k: int = 0):
        """tokens: (B_local, s0) EQUAL-LENGTH prompts (no padding support:
        prefill reads the last column's logits and the cache mask is
        position-only); returns (B_local, n_new).

        ``sampling``/``top_k`` are trace-static (they change the program
        structure); ``temperature`` and the PRNG ``key`` are RUNTIME values
        so new seeds/temperatures reuse the compiled program.  Keys fold
        per step AND per dp shard so every row draws independently."""
        stage_params = {k: v[0] for k, v in params.items() if _is_layer_param(k)}
        b, s0 = tokens.shape
        L = stage_params["wq"].shape[0]  # pp-local layer count
        kv_local = stage_params["wk"].shape[2]  # tp-local KV head count
        kcs = jnp.zeros((L, b, kv_local, S_max, cfg.d_head), cdt)
        vcs = jnp.zeros_like(kcs)

        # prefill: one batched pass over the prompt
        base_key = jax.random.fold_in(key, lax.axis_index("dp"))

        def pick(step_logits, step_idx):
            """(B, V) logits → (B,) next tokens."""
            if not sampling:
                return jnp.argmax(step_logits, axis=-1).astype(jnp.int32)
            scaled = step_logits.astype(jnp.float32) / temperature
            if top_k > 0:
                # k-th largest as threshold via partial selection — a full
                # vocab sort per decoded token would dominate the hot path
                kth = lax.top_k(scaled, top_k)[0][:, -1:]
                scaled = jnp.where(scaled >= kth, scaled, -1e30)
            step_key = jax.random.fold_in(base_key, step_idx)
            return jax.random.categorical(step_key, scaled, axis=-1).astype(jnp.int32)

        x = params["embed"][tokens]
        if cfg.pos_emb == "learned":
            x = x + params["pos"][jnp.arange(s0)]
        # prefill: no-drop serving capacity by default (cf = n_experts ⇒
        # capacity = token count — no prompt token ever loses its MLP
        # contribution, and output is mesh-independent); opt into
        # memory-bounded training semantics via prefill_capacity_factor
        prefill_cf = (
            float(cfg.n_experts)
            if cfg.prefill_capacity_factor is None
            else cfg.prefill_capacity_factor
        )
        x, kcs, vcs = full_stack(
            stage_params, x.astype(cdt), kcs, vcs, 0, prefill_cf
        )
        last = pick(logits_of(params, x)[:, -1, :], 0)

        def step(carry, i):
            kcs, vcs, tok, pos = carry
            x = params["embed"][tok]
            if cfg.pos_emb == "learned":
                x = x + params["pos"][pos]
            x = x[:, None, :].astype(cdt)
            # per-token steps: serving capacity (no drops at tiny t)
            x, kcs, vcs = full_stack(
                stage_params, x, kcs, vcs, pos, float(cfg.n_experts)
            )
            nxt = pick(logits_of(params, x)[:, -1, :], i + 1)
            return (kcs, vcs, nxt, pos + 1), tok

        # step k consumes g_k and computes g_{k+1}; emitting the consumed
        # token makes toks exactly [g_1 .. g_n] (the final compute is spare)
        _, toks = lax.scan(
            step, (kcs, vcs, last, jnp.asarray(s0, jnp.int32)),
            jnp.arange(n_new),
        )
        return toks.T  # (B_local, n_new)

    specs = param_specs(cfg)

    import functools

    @functools.lru_cache(maxsize=16)
    def _compiled(n_new: int, sampling: bool, top_k: int):
        # jit handles prompt-shape (s0) caching; only program STRUCTURE
        # (n_new, greedy-vs-sampling, top_k width) keys distinct compiles —
        # seed and temperature are runtime inputs
        return jax.jit(
            jax.shard_map(
                lambda p, t, temp, key: gen_fn(
                    p, t, temp, key, n_new, sampling, top_k
                ),
                mesh=mesh,
                in_specs=(specs, P("dp"), P(), P()),
                out_specs=P("dp"),
                check_vma=False,
            )
        )

    def generate(
        params,
        prompt: np.ndarray,
        n_new: int,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
    ) -> np.ndarray:
        """prompt: (B, s0) EQUAL-LENGTH prompts, B divisible by dp.

        ``temperature == 0`` (default) decodes greedily; ``temperature > 0``
        samples, optionally truncated to the ``top_k`` most likely tokens,
        deterministically for a given ``seed``.  Changing seed or
        temperature reuses the compiled program."""
        prompt = np.asarray(prompt, dtype=np.int32)
        b, s0 = prompt.shape
        if s0 + n_new > S_max:
            raise ValueError(f"{s0}+{n_new} exceeds max_seq {S_max}")
        dp = mesh.shape.get("dp", 1)
        if b % dp:
            raise ValueError(f"batch {b} not divisible by dp={dp}")
        if top_k > cfg.vocab_size:
            raise ValueError(f"top_k={top_k} exceeds vocab_size {cfg.vocab_size}")
        sampling = temperature > 0.0
        new = np.asarray(
            _compiled(n_new, sampling, int(top_k) if sampling else 0)(
                params,
                jnp.asarray(prompt),
                jnp.asarray(max(float(temperature), 1e-9), jnp.float32),
                jax.random.PRNGKey(int(seed)),
            )
        )
        return np.concatenate([prompt, new], axis=1)

    return generate


def build_train_step(
    cfg: TransformerConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    donate: bool = True,
) -> Callable:
    """One compiled SPMD train step:
    (params, opt_state, tokens, targets) → (params, opt_state, loss).

    Gradient sync: per-parameter psum over exactly the mesh axes the
    parameter is replicated on (the DistributedOptimizer semantics of the
    reference, generalized to a 4-D mesh).  The optimizer update runs on
    the sharded views under GSPMD propagation outside the shard_map.
    """
    validate_mesh(cfg, mesh)
    specs = param_specs(cfg)

    def loss_and_grad(params, tokens, targets):
        # With VMA checking on, shard_map AD handles gradient sync itself:
        # cotangents of replicated (invariant-typed) params are psum'd over
        # exactly the axes they're replicated on — the DistributedOptimizer
        # allreduce falls out of the type system, no manual collectives.
        return jax.value_and_grad(
            lambda p: _local_loss(cfg, mesh, p, tokens, targets)
        )(params)

    shmapped = jax.shard_map(
        loss_and_grad,
        mesh=mesh,
        in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=(P(), specs),
        check_vma=True,
    )

    def step(params, opt_state, tokens, targets):
        loss, grads = shmapped(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())
