"""VGG family (flax) — the reference's communication-bound benchmark model
(docs/performance.md:3-12: VGG-16, +100% over Horovod because its huge
dense layers stress the gradient path — exactly what the PS/compression
pipeline accelerates)."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

_CFG16 = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"]
_CFG11 = [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"]


class VGG(nn.Module):
    cfg: Sequence
    num_classes: int = 1000
    hidden: int = 4096
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME", dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def VGG16(**kw) -> VGG:
    return VGG(cfg=_CFG16, **kw)


def VGG11(**kw) -> VGG:
    return VGG(cfg=_CFG11, **kw)


def VGGTiny(**kw) -> VGG:
    kw.setdefault("num_classes", 10)
    kw.setdefault("hidden", 64)
    return VGG(cfg=[8, "M", 16, "M"], **kw)
