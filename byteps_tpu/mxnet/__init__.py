"""MXNet plugin façade.

The mxnet-dependent surface lives in :mod:`byteps_tpu.mxnet.plugin`
(imported lazily so the pure policy helpers in ``_naming`` stay
importable — and tested — on hosts without mxnet, while
``import byteps_tpu.mxnet`` itself stays cheap).  Attribute access
forwards to the plugin, so the reference usage pattern

    import byteps_tpu.mxnet as bps
    bps.init(); trainer = bps.DistributedTrainer(...)

works unchanged (byteps/mxnet/__init__.py surface); the first touch
raises the underlying ImportError when mxnet is missing.
"""

from __future__ import annotations

_SURFACE = {
    "init", "shutdown", "suspend", "resume",
    "rank", "size", "local_rank", "local_size",
    "byteps_declare_tensor", "byteps_push_pull",
    "DistributedOptimizer", "DistributedTrainer",
    "broadcast_parameters", "Compression", "parameter_index",
}


def __getattr__(name: str):
    if name in _SURFACE:
        from byteps_tpu.mxnet import plugin

        return getattr(plugin, name)
    raise AttributeError(f"module 'byteps_tpu.mxnet' has no attribute {name!r}")


def __dir__():
    return sorted(_SURFACE)
