"""Pure policy helpers for the MXNet plugin — importable (and tested)
without mxnet installed.

The reference keys gradients/parameters by index with a fixed priority
policy (mxnet/__init__.py:52-74: ``gradient_<i>`` at priority ``-i``,
``parameter_<i>`` at priority 0) so the first layers' gradients — needed
first by the next step's forward — win the scheduler.  The trainer-side
compression-params translation (mxnet/__init__.py:236-290) becomes
declare kwargs here (our declare takes kwargs directly instead of
stashing ``byteps_*`` attributes on gluon Parameters).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from byteps_tpu.compression.registry import translate_compression_params


def gradient_name(index: int) -> str:
    return f"gradient_{index}"


def parameter_name(index: int) -> str:
    return f"parameter_{index}"


def weight_name(index: int) -> str:
    return f"weight_{index}"


def gradient_priority(index: int) -> int:
    """Earlier parameters sync at higher priority (reference
    mxnet/__init__.py:56: ``priority=-index``)."""
    return -index


def trainer_compression_kwargs(
    compression_params: Optional[Dict],
    optimizer_params: Optional[Dict],
) -> Tuple[Dict[str, str], Dict, bool]:
    """(declare kwargs, cleaned optimizer_params, use_fp16_intra).

    Mirrors DistributedTrainer._register_compressor semantics
    (mxnet/__init__.py:236-321): ``momentum`` compression lifts the
    optimizer's momentum coefficient into the compressor chain and
    removes it from the local optimizer (the server-side chain applies
    it once, pre-error-feedback); ``fp16`` selects level-1 intra-node
    compression independent of the level-2 codec.
    """
    compression_params = dict(compression_params or {})
    optimizer_params = dict(optimizer_params or {})
    use_fp16 = bool(compression_params.pop("fp16", False))
    if "compressor" not in compression_params:
        return {}, optimizer_params, use_fp16
    if compression_params.get("momentum"):
        if "momentum_mu" not in compression_params:
            if "momentum" not in optimizer_params:
                raise KeyError(
                    "momentum compression requires the optimizer's momentum "
                    "coefficient (optimizer_params['momentum'] or "
                    "compression_params['momentum_mu'])"
                )
            compression_params["momentum_mu"] = optimizer_params.pop("momentum")
    kwargs = translate_compression_params(compression_params)
    return kwargs, optimizer_params, use_fp16
