"""Level-1 (intra-node, framework-side) gradient compression for the
MXNet plugin — parity with byteps/mxnet/compression.py:
``Compression.none`` and ``Compression.fp16`` (cast floating grads to
fp16 for the wire, cast back after aggregation)."""

from __future__ import annotations

import mxnet as mx


class Compressor:
    """Interface: compress(tensor) -> (tensor, ctx); decompress(tensor, ctx)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if dtype in (mx.np.float32 if hasattr(mx, "np") else "float32", "float32", "float64"):
            return tensor.astype("float16"), dtype
        return tensor, dtype

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
