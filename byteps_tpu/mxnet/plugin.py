"""MXNet plugin — Horovod-compatible BytePS surface for MXNet/Gluon.

Parity surface (reference byteps/mxnet/__init__.py:35-360, ops.py:82-120):

    init / shutdown / suspend / resume / rank / size / local_rank / local_size
    byteps_declare_tensor(name, **kwargs)
    byteps_push_pull(tensor, version, priority, name, is_average)
    DistributedOptimizer (sync grads; async pushes weight deltas)
    DistributedTrainer (gluon) with ``compression_params``
    broadcast_parameters(params, root_rank)

TPU-native differences from the reference:

- The reference enqueues push_pull as an async op on the MXNet engine
  with ``FnProperty::kCPUPrioritized`` (ops.cc:30-80).  Here the byteps
  engine owns priority scheduling itself, so the NDArray is handed to
  the engine (D2H staged, partitioned, scheduled by ``priority``) and
  written back in place; ``wait_to_read()`` semantics hold because the
  write-back completes before return.
- Compression config travels as declare kwargs (the engine's registry
  consumes the same ``byteps_*`` keys the reference serializes to its
  server, operations.cc:396-408) instead of attribute-stashing on gluon
  Parameters.
- No ``lr.s`` mmap file: the vanilla-error-feedback lr scaling is fed
  through the registry's ``set_lr`` (error_feedback.py), so the trainer
  just calls that on step.
"""

from __future__ import annotations

import mxnet as mx
import numpy as np

from byteps_tpu import api as _api
from byteps_tpu.api import (
    init,
    local_rank,
    local_size,
    rank,
    resume,
    shutdown,
    size,
    suspend,
)
from byteps_tpu.mxnet._naming import (
    gradient_name,
    gradient_priority,
    parameter_name,
    trainer_compression_kwargs,
    weight_name,
)
from byteps_tpu.mxnet.compression import Compression

__all__ = [
    "init", "shutdown", "suspend", "resume",
    "rank", "size", "local_rank", "local_size",
    "byteps_declare_tensor", "byteps_push_pull",
    "DistributedOptimizer", "DistributedTrainer",
    "broadcast_parameters", "Compression",
]

parameter_index = 0


def byteps_declare_tensor(name: str, **kwargs) -> int:
    """Declare ``name`` (stable key assignment; compression kwargs ride
    along exactly like ops.py:82-120)."""
    return _api.declare_tensor(name, **{k: str(v) for k, v in kwargs.items()})


def byteps_push_pull(
    tensor,
    version: int = 0,
    priority: int = 0,
    name: str = None,
    is_average: bool = True,
):
    """In-place cross-worker sum (mean when ``is_average``) of an
    NDArray through the PS engine."""
    if name is None:
        raise ValueError("byteps_push_pull requires a name (cross-worker key)")
    out = _api.push_pull(
        tensor.asnumpy(), name=name, average=is_average, priority=priority
    )
    tensor[:] = mx.nd.array(np.asarray(out), dtype=tensor.dtype, ctx=tensor.context)
    return tensor


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wraps an mx.optimizer.Optimizer: sync mode push_pulls gradients
    before the local update; async mode updates locally then exchanges
    weight deltas through the parameter store
    (mxnet/__init__.py:35-121)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        import os

        self._enable_async = int(os.getenv("BYTEPS_ENABLE_ASYNC", "0")) != 0
        if self._enable_async:
            assert int(os.getenv("DMLC_NUM_WORKER", "1")) > 1, (
                "Async is only valid for distributed training"
            )

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_push_pull(self, index, grad):
        if isinstance(index, (tuple, list)):
            for i, idx in enumerate(index):
                byteps_declare_tensor(gradient_name(idx))
                byteps_push_pull(
                    grad[i], priority=gradient_priority(idx),
                    name=gradient_name(idx), is_average=True,
                )
        else:
            byteps_declare_tensor(gradient_name(index))
            byteps_push_pull(
                grad, priority=gradient_priority(index),
                name=gradient_name(index), is_average=True,
            )

    def _do_push_pull_param(self, index, delta_weight):
        if isinstance(index, (tuple, list)):
            for i, idx in enumerate(index):
                byteps_declare_tensor(weight_name(idx))
                byteps_push_pull(
                    delta_weight[i], priority=gradient_priority(idx),
                    name=weight_name(idx), is_average=False,
                )
        else:
            byteps_declare_tensor(weight_name(index))
            byteps_push_pull(
                delta_weight, priority=gradient_priority(index),
                name=weight_name(index), is_average=False,
            )

    def _async_update(self, index, weight, grad, state, update_fn):
        # mxnet passes either a scalar index + NDArray or parallel lists
        # (same duality _do_push_pull handles); iterating a bare NDArray
        # would walk its rows, so normalize to lists first
        ws = [weight] if not isinstance(index, (tuple, list)) else weight
        temp = [w.copy() for w in ws]
        update_fn(index, weight, grad, state)
        for w, t in zip(ws, temp):
            w.__isub__(t)  # w now holds the local delta
        self._do_push_pull_param(index, weight)

    def update(self, index, weight, grad, state):
        if self._enable_async:
            self._async_update(index, weight, grad, state, self._optimizer.update)
        else:
            self._do_push_pull(index, grad)
            self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        if self._enable_async:
            self._async_update(
                index, weight, grad, state, self._optimizer.update_multi_precision
            )
        else:
            self._do_push_pull(index, grad)
            self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Root's values win: non-root zeroes its copy, push_pull sums
    (mxnet/__init__.py:124-161 semantics — broadcast = zero + sum)."""
    global parameter_index

    if not isinstance(params, dict):
        raise ValueError(f"Invalid params of type: {type(params)}")
    tensors = [p for _, p in sorted(params.items())]
    for tensor in tensors:
        byteps_declare_tensor(parameter_name(parameter_index))
        if rank() != root_rank:
            tensor.__imul__(0)
        byteps_push_pull(
            tensor, priority=0, name=parameter_name(parameter_index),
            is_average=False,
        )
        parameter_index += 1
    for tensor in tensors:
        tensor.wait_to_read()


class DistributedTrainer(mx.gluon.Trainer):
    """gluon.Trainer whose gradient aggregation runs through the byteps
    engine instead of a kvstore, with level-2 compression configured per
    parameter via ``compression_params``
    (mxnet/__init__.py:164-345)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 root_rank: int = 0, compression_params=None):
        if isinstance(optimizer, DistributedOptimizer):
            optimizer = optimizer._optimizer

        param_list = params
        if isinstance(params, dict):
            param_list = [params[key] for key in sorted(params.keys())]

        declare_kwargs, optimizer_params, use_fp16 = trainer_compression_kwargs(
            compression_params, optimizer_params
        )
        self._intra_compressor = Compression.fp16 if use_fp16 else Compression.none

        super().__init__(
            param_list, optimizer, optimizer_params=optimizer_params, kvstore=None
        )

        self._bps_size = size()
        self.root_rank = root_rank
        for i, param in enumerate(self._params):
            byteps_declare_tensor(parameter_name(i))
            if param.grad_req != "null":
                byteps_declare_tensor(gradient_name(i), **declare_kwargs)

    def step(self, batch_size, ignore_stale_grad=False):
        # grads get normalized by batch_size AND worker count inside
        # _allreduce_grads; _scale=batch_size stops gluon re-normalizing
        self._scale = batch_size
        super().step(batch_size, ignore_stale_grad)

    def _allreduce_grads(self):
        # vanilla-EF lr scaling (replaces the reference's lr.s mmap)
        _api.set_compression_lr(self.learning_rate)
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                grad = param.list_grad()[0]
                grad *= 1.0 / (self._scale * self._bps_size)
                compressed, ctx = self._intra_compressor.compress(grad)
                byteps_push_pull(
                    compressed, is_average=False,
                    name=gradient_name(i), priority=gradient_priority(i),
                )
                param.list_grad()[0][:] = self._intra_compressor.decompress(
                    compressed, ctx
                )

    def _init_params(self):
        tensors = []
        for param in self._params_to_init:
            if param._deferred_init:
                tensors.append(param)
            else:
                arrs = param._check_and_get(param._data, list)
                idx = self._param2idx[param.name]
                if rank() != self.root_rank:
                    arrs[0].__imul__(0)
                byteps_push_pull(
                    arrs[0], priority=0, name=parameter_name(idx),
                    is_average=False,
                )
        self._params_to_init = tensors
