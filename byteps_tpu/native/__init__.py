"""ctypes bindings for the native C++ core (reducer + compression codecs).

The library is built from byteps_tpu/native/*.cc via the Makefile; import
succeeds (``HAVE_NATIVE = False``) even when the .so is missing so pure-
Python fallbacks can take over (the reference hard-requires its C++ core;
we degrade gracefully for portability but production runs should build it).

Build: ``make -C byteps_tpu/native`` (auto-attempted on first import).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libbyteps_tpu.so")

#: completion-callback signature of the native worker client
#: (ps_client.cc bpsc_cb_t): (ctx, op, status, flags, seq, key, cmd,
#: version, payload_ptr, length, zero_copied).  Since r5 this fires ONLY
#: as the batched-delivery doorbell (op=-2, other args zero); records
#: are then pulled in bulk via ``bpsc_drain``.
BPSC_CALLBACK = ctypes.CFUNCTYPE(
    None,
    ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32,
    ctypes.c_uint32, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64, ctypes.c_int32,
)

#: DrainRec mirror (ps_client.cc — change both together).  64-bit fields
#: first so the C struct has no padding holes; one trailing pad int.
DRAIN_REC_DTYPE = np.dtype([
    ("key", "<u8"), ("len", "<u8"), ("off", "<u8"),
    ("op", "<i4"), ("status", "<i4"), ("flags", "<u4"), ("seq", "<u4"),
    ("cmd", "<u4"), ("version", "<u4"), ("zc", "<i4"), ("_pad", "<i4"),
])
assert DRAIN_REC_DTYPE.itemsize == 56

#: SpanRec mirror (ps_server.cc — change both together): one child-span
#: record drained from the native engine's trace ring via
#: ``bps_native_server_drain_spans`` (docs/observability.md).  ``stripe``
#: is the reducer lane that executed the stage (-1 = a serve/control
#: thread); the drain maps each stripe to its own Perfetto track.
SPAN_REC_DTYPE = np.dtype([
    ("trace", "<u8"), ("parent", "<u8"), ("key", "<u8"),
    ("ts", "<f8"), ("dur", "<f8"), ("kind", "<i4"), ("flags", "<u4"),
    ("stripe", "<i4"), ("_pad", "<u4"),
])
assert SPAN_REC_DTYPE.itemsize == 56

#: SpanKind index order (ps_server.cc) → span names matching the Python
#: server's child-span model (server.py _child_span call sites)
NATIVE_SPAN_KINDS = ("recv", "sum", "publish", "reply", "resync")

#: SpanRec.flags bits
SPAN_FLAG_DEDUPE = 1
SPAN_FLAG_FUSED = 2

_lib: Optional[ctypes.CDLL] = None


def _try_build() -> None:
    """Run make under a file lock: many worker processes import this module
    concurrently on a fresh checkout, and only one should compile."""
    try:
        import fcntl

        with open(os.path.join(_DIR, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            subprocess.run(
                ["make", "-C", _DIR, "-s"],
                check=True,
                capture_output=True,
                timeout=120,
            )
    except Exception:
        pass


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.bps_sum.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_int32]
    lib.bps_sum.restype = c.c_int32
    lib.bps_sum_scaled_f32.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64, c.c_float]
    lib.bps_sum_scaled_f32.restype = c.c_int32
    lib.bps_onebit_size.argtypes = [c.c_int64]
    lib.bps_onebit_size.restype = c.c_int64
    lib.bps_onebit_compress.argtypes = [c.c_void_p, c.c_int64, c.c_void_p, c.c_int32]
    lib.bps_onebit_compress.restype = c.c_int64
    lib.bps_onebit_decompress.argtypes = [c.c_void_p, c.c_int64, c.c_void_p]
    lib.bps_onebit_decompress.restype = c.c_int32
    lib.bps_topk_compress.argtypes = [c.c_void_p, c.c_int64, c.c_int64, c.c_void_p]
    lib.bps_topk_compress.restype = c.c_int64
    lib.bps_topk_decompress.argtypes = [c.c_void_p, c.c_int64, c.c_void_p, c.c_int64]
    lib.bps_topk_decompress.restype = c.c_int32
    lib.bps_topk_sum_into.argtypes = [c.c_void_p, c.c_int64, c.c_void_p, c.c_int64]
    lib.bps_topk_sum_into.restype = c.c_int32
    lib.bps_randomk_compress.argtypes = [
        c.c_void_p, c.c_int64, c.c_int64, c.c_uint64, c.c_uint64, c.c_void_p,
    ]
    lib.bps_randomk_compress.restype = c.c_int64
    lib.bps_dithering_compress.argtypes = [
        c.c_void_p, c.c_int64, c.c_int32, c.c_int32, c.c_int32,
        c.c_uint64, c.c_uint64, c.c_void_p,
    ]
    lib.bps_dithering_compress.restype = c.c_int64
    lib.bps_dithering_decompress.argtypes = [
        c.c_void_p, c.c_int64, c.c_int32, c.c_int32, c.c_void_p,
    ]
    lib.bps_dithering_decompress.restype = c.c_int32
    # native PS server data plane (ps_server.cc) — may be absent in a
    # stale .so; codecs/reducer still work without it
    if hasattr(lib, "bps_native_server_start"):
        lib.bps_native_server_start.argtypes = [c.c_int32, c.c_int32, c.c_int32]
        lib.bps_native_server_start.restype = c.c_int32
        lib.bps_native_server_set_num_workers.argtypes = [c.c_int32, c.c_int32]
        lib.bps_native_server_set_num_workers.restype = None
        lib.bps_native_server_stop.argtypes = [c.c_int32]
        lib.bps_native_server_stop.restype = None
    if hasattr(lib, "bps_native_server_start_unix"):
        lib.bps_native_server_start_unix.argtypes = [
            c.c_char_p, c.c_int32, c.c_int32, c.c_int32,
        ]
        lib.bps_native_server_start_unix.restype = c.c_int32
    # protocol-parity surface (FUSED/ledger/RESYNC port): observability
    # counters, the zombie-fence feed, and the golden wire-codec shims
    if hasattr(lib, "bps_native_server_counters"):
        lib.bps_native_server_counters.argtypes = [
            c.c_int32, c.POINTER(c.c_uint64), c.c_int32,
        ]
        lib.bps_native_server_counters.restype = c.c_int32
        lib.bps_native_server_set_live_workers.argtypes = [
            c.c_int32, c.POINTER(c.c_uint8), c.c_int32,
        ]
        lib.bps_native_server_set_live_workers.restype = None
        # elastic resharding plane (docs/robustness.md "migration flow")
        if hasattr(lib, "bps_native_server_set_ownership"):
            lib.bps_native_server_set_ownership.argtypes = [
                c.c_int32, c.c_int32, c.c_uint32, c.c_int32,
                c.POINTER(c.c_uint64), c.POINTER(c.c_int32),
            ]
            lib.bps_native_server_set_ownership.restype = None
        lib.bps_wire_golden.argtypes = [c.c_void_p, c.c_uint64]
        lib.bps_wire_golden.restype = c.c_int64
        # compressed-wire-path fixtures (may be absent in a stale .so;
        # the golden test skips that lane rather than failing it)
        if hasattr(lib, "bps_wire_golden_compressed"):
            lib.bps_wire_golden_compressed.argtypes = [c.c_void_p, c.c_uint64]
            lib.bps_wire_golden_compressed.restype = c.c_int64
        lib.bps_wire_fused_echo.argtypes = [
            c.c_void_p, c.c_uint64, c.c_void_p, c.c_uint64,
        ]
        lib.bps_wire_fused_echo.restype = c.c_int64
        lib.bps_wire_resync_echo.argtypes = [
            c.c_void_p, c.c_uint64, c.c_void_p, c.c_uint64,
        ]
        lib.bps_wire_resync_echo.restype = c.c_int64
    # observability-parity surface (span drain + histogram feeds) — may
    # be absent in a stale .so; counters/data plane still work without it
    if hasattr(lib, "bps_native_server_drain_spans"):
        lib.bps_native_server_set_trace.argtypes = [c.c_int32, c.c_int32]
        lib.bps_native_server_set_trace.restype = None
        lib.bps_native_server_drain_spans.argtypes = [
            c.c_int32, c.c_void_p, c.c_int32,
        ]
        lib.bps_native_server_drain_spans.restype = c.c_int32
        lib.bps_native_server_metrics_json.argtypes = [
            c.c_int32, c.c_void_p, c.c_uint64,
        ]
        lib.bps_native_server_metrics_json.restype = c.c_int64
        lib.bps_wire_fused_spans_echo.argtypes = [
            c.c_void_p, c.c_uint64, c.POINTER(c.c_uint64), c.c_int64,
        ]
        lib.bps_wire_fused_spans_echo.restype = c.c_int64
        lib.bps_wire_client_frame.argtypes = [
            c.c_int32, c.c_uint32, c.c_uint64, c.c_uint32, c.c_uint32,
            c.c_uint32, c.c_uint64, c.c_uint64, c.c_void_p, c.c_uint64,
            c.c_void_p, c.c_uint64,
        ]
        lib.bps_wire_client_frame.restype = c.c_int64
    # key-striped reducer plane (ISSUE 7): per-stripe queue-depth feed +
    # the live key→stripe mapping shim — also the layout marker for the
    # 56-byte SpanRec (older libs drained 48-byte records)
    if hasattr(lib, "bps_native_server_stripe_queue_depths"):
        lib.bps_native_server_stripe_queue_depths.argtypes = [
            c.c_int32, c.POINTER(c.c_uint64), c.c_int32,
        ]
        lib.bps_native_server_stripe_queue_depths.restype = c.c_int32
        lib.bps_wire_key_stripe.argtypes = [c.c_uint64, c.c_int32]
        lib.bps_wire_key_stripe.restype = c.c_int32
    if hasattr(lib, "bps_wire_ring_hash"):
        lib.bps_wire_ring_hash.argtypes = [c.c_uint64]
        lib.bps_wire_ring_hash.restype = c.c_uint64
    # end-to-end wire integrity (docs/robustness.md "Wire integrity"):
    # the shared CRC32C (transport.py's ctypes fast path) + the
    # checksummed golden shims — may be absent in a stale .so; the
    # pure-Python CRC takes over and the golden lanes skip
    if hasattr(lib, "bps_wire_crc32c"):
        lib.bps_wire_crc32c.argtypes = [c.c_void_p, c.c_uint64, c.c_uint32]
        lib.bps_wire_crc32c.restype = c.c_uint32
        lib.bps_wire_golden_checksum.argtypes = [c.c_void_p, c.c_uint64]
        lib.bps_wire_golden_checksum.restype = c.c_int64
        lib.bps_wire_client_frame_ck.argtypes = [
            c.c_int32, c.c_uint32, c.c_uint64, c.c_uint32, c.c_uint32,
            c.c_uint32, c.c_uint64, c.c_uint64, c.c_void_p, c.c_uint64,
            c.c_void_p, c.c_uint64,
        ]
        lib.bps_wire_client_frame_ck.restype = c.c_int64
    # lossless wire-frame codec (compression/lossless.py's ctypes fast
    # path + the C/Python parity anchor) — may be absent in a stale .so;
    # the pure-Python codec takes over
    if hasattr(lib, "bps_wire_lossless_compress"):
        lib.bps_wire_lossless_compress.argtypes = [
            c.c_char_p, c.c_uint64, c.c_void_p, c.c_uint64,
        ]
        lib.bps_wire_lossless_compress.restype = c.c_int64
        lib.bps_wire_lossless_decompress.argtypes = [
            c.c_char_p, c.c_uint64, c.c_void_p, c.c_uint64,
        ]
        lib.bps_wire_lossless_decompress.restype = c.c_int64
    # native worker client data plane (ps_client.cc) — may be absent in a
    # stale .so; the pure-Python client covers every van without it
    if hasattr(lib, "bpsc_create"):
        lib.bpsc_create.argtypes = [c.c_char_p, c.c_int32, c.c_int32, c.c_int32]
        lib.bpsc_create.restype = c.c_int64
        lib.bpsc_set_cb.argtypes = [c.c_int64, BPSC_CALLBACK, c.c_void_p]
        lib.bpsc_set_cb.restype = None
        lib.bpsc_alloc_seq.argtypes = [c.c_int64, c.c_void_p, c.c_uint64]
        lib.bpsc_alloc_seq.restype = c.c_int64
        lib.bpsc_send.argtypes = [
            c.c_int64, c.c_int32, c.c_uint32, c.c_uint64, c.c_uint32,
            c.c_uint32, c.c_uint32, c.c_void_p, c.c_uint64,
        ]
        lib.bpsc_send.restype = c.c_int32
        lib.bpsc_close.argtypes = [c.c_int64]
        lib.bpsc_close.restype = None
        if hasattr(lib, "bpsc_drain"):
            lib.bpsc_drain.argtypes = [
                c.c_int64, c.c_void_p, c.c_int64, c.c_void_p, c.c_uint64,
            ]
            lib.bpsc_drain.restype = c.c_int64
        if hasattr(lib, "bpsc_send2"):
            # trace-context-aware send + the client histogram feed
            lib.bpsc_send2.argtypes = [
                c.c_int64, c.c_int32, c.c_uint32, c.c_uint64, c.c_uint32,
                c.c_uint32, c.c_uint32, c.c_void_p, c.c_uint64, c.c_uint64,
                c.c_uint64,
            ]
            lib.bpsc_send2.restype = c.c_int32
            lib.bpsc_metrics_json.argtypes = [c.c_int64, c.c_void_p, c.c_uint64]
            lib.bpsc_metrics_json.restype = c.c_int64
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    autobuild = os.environ.get("BYTEPS_NATIVE_AUTOBUILD", "1") != "0"
    if autobuild:
        # the .so is not committed (build artifact); make is a fast no-op
        # when sources are unchanged and rebuilds on .cc edits
        _try_build()
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None  # corrupt/partial .so → pure-Python fallbacks
    if not hasattr(lib, "bps_wire_lossless_compress") and autobuild:
        # stale library from before the newest entry points (currently
        # the lossless wire-frame codec plane): rebuild, then
        # load via a temp COPY — dlopen dedups by path/inode, so
        # reloading the original path can hand back the old mapping
        _try_build()
        try:
            import shutil
            import tempfile

            tmp = tempfile.NamedTemporaryFile(
                suffix=".so", prefix="libbyteps_tpu_", delete=False
            )
            tmp.close()
            shutil.copy(_LIB_PATH, tmp.name)
            fresh = ctypes.CDLL(tmp.name)
            if hasattr(fresh, "bps_wire_lossless_compress"):
                lib = fresh
        except OSError:
            pass
    _lib = _bind(lib)
    return _lib


def get_lib() -> Optional[ctypes.CDLL]:
    return _load()


HAVE_NATIVE = _load() is not None

#: ``bps_native_server_counters`` index order (ps_server.cc
#: ``NativeCounter`` — change both together).  Distinct ``native_``-
#: prefixed names: in-process test clusters share one counter registry
#: between worker and server roles, and the worker side already bumps
#: ``wire_rpc``/``fused_frames``/``push_dedup`` — colliding names would
#: double-count (docs/observability.md).
NATIVE_COUNTER_NAMES = (
    "native_wire_rpc",
    "native_fused_frames",
    "native_fused_keys",
    "native_push_dedup",
    "native_init_replay_ack",
    "native_resync_query",
    "native_zombie_reject",
    "native_span_drop",
    "native_wrong_owner",
    "native_job_reject",
    "native_async_reject",
    "native_checksum_fail",
    "native_checksum_conn_drop",
    "native_server_opt_reject",
    "native_lossless_fail",
)


def native_server_counters(server_id: int) -> dict:
    """One native server instance's observability counters as
    ``{name: int}``; empty once the instance is stopped (or the lib
    predates the getter) — the ``get_robustness_counters()`` merge path
    (see :meth:`RobustnessCounters.register_provider`)."""
    lib = _load()
    if lib is None or not hasattr(lib, "bps_native_server_counters"):
        return {}
    out = (ctypes.c_uint64 * len(NATIVE_COUNTER_NAMES))()
    n = lib.bps_native_server_counters(
        server_id, out, len(NATIVE_COUNTER_NAMES)
    )
    if n <= 0:
        return {}
    return {NATIVE_COUNTER_NAMES[i]: int(out[i]) for i in range(n)}


def _metrics_json(call, ident) -> list:
    """Shared grow-and-retry wrapper for the native metrics-JSON exports
    → the ``register_hist_provider`` record list (empty when the source
    is gone / the lib predates the export / the body is malformed)."""
    import json

    cap = 1 << 16
    for _ in range(8):  # 64 KiB → 8 MiB: bounded growth, no spin
        buf = (ctypes.c_uint8 * cap)()
        n = call(ident, buf, cap)
        if n == -1 or n == 0:
            return []
        if n < 0:
            cap = max(-int(n), cap * 2)
            continue
        try:
            doc = json.loads(bytes(buf[:n]).decode())
        except (ValueError, UnicodeDecodeError):
            return []
        return list(doc.get("histograms") or [])
    return []


def native_server_histograms(server_id: int) -> list:
    """One native server instance's histograms (``native_server_sum_seconds``
    per key, ``native_request_bytes`` per key, ``native_server_publish_seconds``)
    as histogram-provider records — the feed behind
    :meth:`MetricsRegistry.register_hist_provider`
    (docs/observability.md)."""
    lib = _load()
    if lib is None or not hasattr(lib, "bps_native_server_metrics_json"):
        return []
    return _metrics_json(lib.bps_native_server_metrics_json, server_id)


def native_client_histograms(handle: int) -> list:
    """One native client handle's histograms
    (``native_rpc_round_trip_seconds``) as histogram-provider records."""
    lib = _load()
    if lib is None or not hasattr(lib, "bpsc_metrics_json"):
        return []
    return _metrics_json(lib.bpsc_metrics_json, handle)


def native_server_drain_spans(server_id: int, max_recs: int = 4096):
    """Drain the native engine's child-span ring (docs/observability.md):
    returns a structured ndarray of :data:`SPAN_REC_DTYPE` records
    (empty once the instance is stopped or the lib predates the span
    plane).  The caller — NativePSServer's drain loop — replays them
    into the process tracer.  Gated on the striping surface too: a
    pre-striping lib writes 48-byte records the 56-byte dtype would
    mis-decode."""
    lib = _load()
    if (lib is None
            or not hasattr(lib, "bps_native_server_drain_spans")
            or not hasattr(lib, "bps_native_server_stripe_queue_depths")):
        return np.zeros(0, dtype=SPAN_REC_DTYPE)
    recs = np.zeros(max_recs, dtype=SPAN_REC_DTYPE)
    n = lib.bps_native_server_drain_spans(
        server_id, recs.ctypes.data_as(ctypes.c_void_p), max_recs
    )
    if n <= 0:
        return np.zeros(0, dtype=SPAN_REC_DTYPE)
    return recs[:n]


def native_server_stripe_depths(server_id: int) -> list:
    """Current task backlog per reducer stripe of one native server
    instance (the ``native_stripe_queue_depth{stripe}`` gauge feed;
    docs/perf.md hot-stripe note).  Empty once the instance is stopped
    or the lib predates the striping surface."""
    lib = _load()
    if lib is None or not hasattr(lib, "bps_native_server_stripe_queue_depths"):
        return []
    out = (ctypes.c_uint64 * 64)()
    n = lib.bps_native_server_stripe_queue_depths(server_id, out, 64)
    if n <= 0:
        return []
    return [int(out[i]) for i in range(n)]


def key_stripe(key: int, n_stripes: int) -> int:
    """The live key→reducer-stripe mapping (wire.h ``key_stripe``), or
    ``key % n_stripes`` as a stand-in when the lib is unavailable (only
    tests use this helper; the engine always uses the native hash)."""
    lib = _load()
    if lib is None or not hasattr(lib, "bps_wire_key_stripe"):
        return int(key) % max(1, int(n_stripes))
    return int(lib.bps_wire_key_stripe(key, n_stripes))


def native_server_set_trace(server_id: int, on: bool) -> None:
    """Mirror the wrapper's tracing decision (cfg.trace_on &&
    cfg.trace_spans) into the C++ engine's span gate."""
    lib = _load()
    if lib is not None and hasattr(lib, "bps_native_server_set_trace"):
        lib.bps_native_server_set_trace(server_id, int(bool(on)))


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class cpu_reducer:
    """Namespace mirroring CpuReducer (cpu_reducer.h:40-205)."""

    @staticmethod
    def sum_into(dst: np.ndarray, src: np.ndarray) -> None:
        """dst[:len(src)] += src (native when available)."""
        from byteps_tpu.common.types import to_datatype

        lib = _load()
        n = src.size
        if lib is None or not dst.flags.c_contiguous or not src.flags.c_contiguous:
            np.add(dst[:n], src, out=dst[:n])
            return
        rc = lib.bps_sum(_ptr(dst), _ptr(src), n, int(to_datatype(src.dtype)))
        if rc != 0:  # unsupported dtype → numpy
            np.add(dst[:n], src, out=dst[:n])
