// Gradient compression codecs — native C++ core.
//
// TPU-native re-design of byteps/common/compressor/impl/* (SURVEY §2.2):
//   onebit    — sign compression packed 32:1 with optional L1 scaling
//               (onebit.cc)
//   topk      — largest-k (index, value) pairs (topk.cc)
//   randomk   — random-k with a shared xorshift128+ seed so worker and
//               server draw identical indices (randomk.cc, utils.h RNG)
//   dithering — stochastic quantization, linear or natural (power-of-two)
//               level partition, max or L2 norm (dithering.cc)
//
// All codecs run on the fp32 host staging buffer (compression happens
// post-local-reduce, pre-PUSH — docs/gradient-compression.md).  C ABI via
// ctypes; buffers are caller-allocated numpy arrays.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// xorshift128+ — must match byteps_tpu/compression/rng.py bit-for-bit
// ---------------------------------------------------------------------------

static inline uint64_t xorshift128p(uint64_t* s) {
  uint64_t x = s[0];
  const uint64_t y = s[1];
  s[0] = y;
  x ^= x << 23;
  s[1] = x ^ y ^ (x >> 17) ^ (y >> 26);
  return s[1] + y;
}

// ---------------------------------------------------------------------------
// onebit: [f32 scale][u32 packed signs]  (bit set = negative)
// ---------------------------------------------------------------------------

int64_t bps_onebit_size(int64_t n) { return 4 + 4 * ((n + 31) / 32); }

int64_t bps_onebit_compress(const float* in, int64_t n, uint8_t* out,
                            int32_t scaled) {
  float scale = 1.0f;
  if (scaled) {
    double l1 = 0.0;
#pragma omp parallel for reduction(+ : l1) schedule(static)
    for (int64_t i = 0; i < n; ++i) l1 += std::fabs((double)in[i]);
    scale = n > 0 ? (float)(l1 / (double)n) : 1.0f;
  }
  std::memcpy(out, &scale, 4);
  uint32_t* words = (uint32_t*)(out + 4);
  int64_t nwords = (n + 31) / 32;
#pragma omp parallel for schedule(static)
  for (int64_t w = 0; w < nwords; ++w) {
    uint32_t bits = 0;
    int64_t base = w * 32;
    int64_t end = std::min<int64_t>(base + 32, n);
    for (int64_t i = base; i < end; ++i) {
      if (std::signbit(in[i])) bits |= (1u << (i - base));
    }
    words[w] = bits;
  }
  return bps_onebit_size(n);
}

int32_t bps_onebit_decompress(const uint8_t* in, int64_t n, float* out) {
  float scale;
  std::memcpy(&scale, in, 4);
  const uint32_t* words = (const uint32_t*)(in + 4);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bit = (words[i / 32] >> (i % 32)) & 1u;
    out[i] = bit ? -scale : scale;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// topk: [i32 idx, f32 val] * k
// ---------------------------------------------------------------------------

int64_t bps_topk_size(int64_t k) { return 8 * k; }

int64_t bps_topk_compress(const float* in, int64_t n, int64_t k,
                          uint8_t* out) {
  if (k > n) k = n;
  std::vector<int32_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  // tie-break on index (ascending) so equal |magnitudes| at the k-th
  // boundary select deterministically — and identically to the device
  // packer (jax.lax.top_k favors lower indices) and the numpy fallback
  std::nth_element(idx.begin(), idx.begin() + k, idx.end(),
                   [&](int32_t a, int32_t b) {
                     float fa = std::fabs(in[a]), fb = std::fabs(in[b]);
                     return fa > fb || (fa == fb && a < b);
                   });
  // deterministic order: sort the selected k by index
  std::sort(idx.begin(), idx.begin() + k);
  for (int64_t j = 0; j < k; ++j) {
    int32_t i = idx[j];
    std::memcpy(out + 8 * j, &i, 4);
    std::memcpy(out + 8 * j + 4, &in[i], 4);
  }
  return 8 * k;
}

int32_t bps_topk_decompress(const uint8_t* in, int64_t k, float* out,
                            int64_t n) {
  std::memset(out, 0, (size_t)n * 4);
  for (int64_t j = 0; j < k; ++j) {
    int32_t i;
    float v;
    std::memcpy(&i, in + 8 * j, 4);
    std::memcpy(&v, in + 8 * j + 4, 4);
    if (i >= 0 && i < n) out[i] = v;
  }
  return 0;
}

// sum a compressed topk payload into a dense fp32 accumulator (server-side
// SUM_RECV without densifying first)
int32_t bps_topk_sum_into(const uint8_t* in, int64_t k, float* acc,
                          int64_t n) {
  for (int64_t j = 0; j < k; ++j) {
    int32_t i;
    float v;
    std::memcpy(&i, in + 8 * j, 4);
    std::memcpy(&v, in + 8 * j + 4, 4);
    if (i >= 0 && i < n) acc[i] += v;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// randomk: same payload as topk; indices drawn by shared-seed xorshift128+
// ---------------------------------------------------------------------------

int64_t bps_randomk_compress(const float* in, int64_t n, int64_t k,
                             uint64_t s0, uint64_t s1, uint8_t* out) {
  if (k > n) k = n;
  uint64_t st[2] = {s0 ? s0 : 0x9E3779B97F4A7C15ull, s1 ? s1 : 0xBF58476D1CE4E5B9ull};
  for (int64_t j = 0; j < k; ++j) {
    int32_t i = (int32_t)(xorshift128p(st) % (uint64_t)n);
    std::memcpy(out + 8 * j, &i, 4);
    std::memcpy(out + 8 * j + 4, &in[i], 4);
  }
  return 8 * k;
}

// ---------------------------------------------------------------------------
// dithering: [f32 norm][i8 signed level] * n
//   s levels; linear partition l_j = j/s, or natural partition with levels
//   at powers of two; norm = max|x| or L2
// ---------------------------------------------------------------------------

int64_t bps_dithering_size(int64_t n) { return 4 + n; }

int64_t bps_dithering_compress(const float* in, int64_t n, int32_t s,
                               int32_t natural, int32_t l2, uint64_t s0,
                               uint64_t s1, uint8_t* out) {
  double norm = 0.0;
  if (l2) {
    for (int64_t i = 0; i < n; ++i) norm += (double)in[i] * in[i];
    norm = std::sqrt(norm);
  } else {
    for (int64_t i = 0; i < n; ++i)
      norm = std::max(norm, (double)std::fabs(in[i]));
  }
  if (norm == 0.0) norm = 1.0;
  float normf = (float)norm;
  std::memcpy(out, &normf, 4);
  int8_t* lv = (int8_t*)(out + 4);
  uint64_t st[2] = {s0 ? s0 : 0x9E3779B97F4A7C15ull, s1 ? s1 : 0xBF58476D1CE4E5B9ull};
  for (int64_t i = 0; i < n; ++i) {
    double p = std::fabs((double)in[i]) / norm;  // in [0,1]
    double u = (double)(xorshift128p(st) >> 11) * (1.0 / 9007199254740992.0);
    int32_t level;
    if (natural) {
      // natural partition: levels 0 and 2^{-j}, j = s-1..0
      if (p <= 0.0) {
        level = 0;
      } else {
        double lg = std::log2(p);
        int32_t j = (int32_t)std::floor(lg);        // 2^j <= p < 2^{j+1}
        if (j >= 0) {
          level = s;  // p >= 1 → top level
        } else if (j < -s) {
          // below the smallest level: round to 0 or 2^{-s}
          double lo = 0.0, hi = std::pow(2.0, -(double)s);
          level = (p - lo) / (hi - lo) > u ? 1 : 0;
        } else {
          double lo = std::pow(2.0, (double)j);
          double hi = std::pow(2.0, (double)j + 1);
          int32_t jl = s + j;  // index of lo level (1..s-1)
          level = (p - lo) / (hi - lo) > u ? jl + 1 : jl;
        }
      }
    } else {
      // linear partition: levels j/s
      double scaled = p * s;
      int32_t fl = (int32_t)std::floor(scaled);
      double frac = scaled - fl;
      level = fl + (frac > u ? 1 : 0);
      if (level > s) level = s;
    }
    lv[i] = (int8_t)(std::signbit(in[i]) ? -level : level);
  }
  return 4 + n;
}

int32_t bps_dithering_decompress(const uint8_t* in, int64_t n, int32_t s,
                                 int32_t natural, float* out) {
  float norm;
  std::memcpy(&norm, in, 4);
  const int8_t* lv = (const int8_t*)(in + 4);
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    int32_t level = lv[i];
    int32_t a = level < 0 ? -level : level;
    double mag;
    if (natural) {
      mag = a == 0 ? 0.0 : std::pow(2.0, (double)(a - s));
    } else {
      mag = (double)a / (double)s;
    }
    out[i] = (float)((level < 0 ? -mag : mag) * norm);
  }
  return 0;
}

}  // extern "C"
