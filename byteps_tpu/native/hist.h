// Native fixed-bucket histograms — the C++ twin of
// core/telemetry.py's Histogram (docs/observability.md).  Buckets are
// cumulative-upper-bound ("le") semantics with an implicit +Inf slot;
// the bound tables below MUST match telemetry.LATENCY_BUCKETS /
// SIZE_BUCKETS exactly, because the Python histogram-provider seam
// merges these raw counts into the same registry families the Python
// engines feed (bucket-merge needs identical bounds).
//
// observe() is lock-free: one linear bound scan (the tables are tiny
// and hot in cache) + three relaxed atomic adds — cheap enough to stay
// always-on in the GIL-free data plane, same always-on contract the
// Python engine's histograms keep.  Sums are stored scaled to an
// integer unit (microseconds for latency, bytes for sizes) so the sum
// can be a single atomic without a compare-exchange loop on double.
#ifndef BYTEPS_TPU_NATIVE_HIST_H_
#define BYTEPS_TPU_NATIVE_HIST_H_

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

namespace bps_hist {

// telemetry.LATENCY_BUCKETS (seconds) — change both together
constexpr double kLatencyBounds[] = {
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1,    0.25,    0.5,    1.0,   2.5,    5.0,   10.0, 30.0,  100.0,
};
constexpr int kLatencyNum = sizeof(kLatencyBounds) / sizeof(double);

// telemetry.SIZE_BUCKETS (bytes) — change both together
constexpr double kSizeBounds[] = {
    64, 256, 1024, 4096, 16384, 65536, 262144, 1048576, 4194304, 16777216,
};
constexpr int kSizeNum = sizeof(kSizeBounds) / sizeof(double);

constexpr int kMaxBuckets = kLatencyNum > kSizeNum ? kLatencyNum : kSizeNum;

struct Hist {
  const double* bounds = kLatencyBounds;
  int nbounds = kLatencyNum;
  double scale = 1e6;  // value → integer sum unit (µs for latency)
  std::atomic<uint64_t> counts[kMaxBuckets + 1] = {};  // +1 = +Inf
  std::atomic<uint64_t> count{0};
  std::atomic<uint64_t> sum_scaled{0};

  void init_size_buckets() {
    bounds = kSizeBounds;
    nbounds = kSizeNum;
    scale = 1.0;  // sums stay in bytes
  }

  void observe(double v) {
    int i = 0;
    while (i < nbounds && v > bounds[i]) ++i;
    counts[i].fetch_add(1, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
    double s = v * scale;
    sum_scaled.fetch_add(s > 0 ? (uint64_t)(s + 0.5) : 0,
                         std::memory_order_relaxed);
  }

  // One JSON record for the Python histogram-provider seam
  // (telemetry.MetricsRegistry.register_hist_provider):
  //   {"name": ..., "labels": {...}, "le": [...], "b": [...N+1 raw...],
  //    "sum": <seconds-or-bytes>, "count": n}
  // Appends nothing (and returns false) when the histogram is empty.
  bool append_json(std::string* out, const char* name,
                   const char* label_key, const std::string& label_val) const {
    uint64_t n = count.load(std::memory_order_relaxed);
    if (n == 0) return false;
    char buf[96];
    if (!out->empty() && out->back() == '}') *out += ", ";
    *out += "{\"name\": \"";
    *out += name;
    *out += "\", \"labels\": {";
    if (label_key) {
      *out += "\"";
      *out += label_key;
      *out += "\": \"" + label_val + "\"";
    }
    *out += "}, \"le\": [";
    for (int i = 0; i < nbounds; ++i) {
      snprintf(buf, sizeof buf, "%s%.17g", i ? ", " : "", bounds[i]);
      *out += buf;
    }
    *out += "], \"b\": [";
    for (int i = 0; i <= nbounds; ++i) {
      snprintf(buf, sizeof buf, "%s%llu", i ? ", " : "",
               (unsigned long long)counts[i].load(std::memory_order_relaxed));
      *out += buf;
    }
    snprintf(buf, sizeof buf, "], \"sum\": %.17g, \"count\": %llu}",
             (double)sum_scaled.load(std::memory_order_relaxed) / scale,
             (unsigned long long)n);
    *out += buf;
    return true;
  }
};

}  // namespace bps_hist

#endif  // BYTEPS_TPU_NATIVE_HIST_H_
