// Native worker-side PS data plane (the C++ half of comm/ps_client.py).
//
// The reference's worker hot path is C++ (core_loops.cc:538-618: ZPush /
// ZPull framing, completion demux, zero-copy into caller SArrays) with
// Python only steering.  This file gives the TPU build the same split:
// lane sockets, 32-byte framing, seq-table demux, and payload receive —
// including pull-into-caller-buffer zero copy — all run on C++ threads
// with no GIL; Python sees one completion callback per message (ctypes
// re-acquires the GIL for the duration of the callback only).
//
// Scope: the worker↔server DATA lanes for the tcp and uds vans,
// including BYTEPS_TCP_STREAMS striping (responses demux into one shared
// seq table, per-key lane pinning preserves per-key FIFO).  The shm van
// keeps its Python client (its bulk path is already syscall-free mmap
// memcpy), and the scheduler link stays Python (low-rate control plane).
//
// Contract with comm/ps_client.py (_NativeServerConn):
//   h   = bpsc_create(host, port, kind, streams)   kind: 0 tcp, 1 uds
//         bpsc_set_cb(h, cb, ctx)                  BEFORE first alloc/send
//   seq = bpsc_alloc_seq(h, sink_ptr, sink_len)    -1 => conn dead
//         bpsc_send(h, op, seq, key, cmd, ver, flags, payload, len)
//         bpsc_close(h)                            joins lanes, frees h
//
// Handles are ids into a global registry holding shared_ptrs: a send
// racing a close (elastic server-swap failure path) resolves its id
// before the close erases it — the object stays alive until the last
// in-flight call returns — or after, in which case the call fails
// cleanly instead of touching freed memory.
//
// Completion delivery is BATCHED (r5): lanes enqueue fixed-size
// completion records (payload bytes owned by the entry; zero-copy
// payloads are already in the caller's sink) and fire the registered
// callback ONCE per empty→non-empty queue transition as a doorbell
// (op=-2, every other argument zero).  Python then drains in bulk:
//
//   n = bpsc_drain(h, recs, max_recs, arena, arena_cap)
//
// fills an array of DrainRec (layout below, mirrored by a numpy dtype
// in native/__init__.py) plus non-zero-copy payload bytes packed into
// the arena at rec.off.  Returns the record count, or -(needed) when
// the FIRST pending payload exceeds arena_cap (caller grows + retries).
// Rationale: a ctypes trampoline costs ~10-30µs per invocation with
// this signature — per-message delivery made the native client ~40%
// slower than the Python client on many-small-message rounds
// (VAN_BENCH r4/r5); one doorbell + one bulk drain per burst amortizes
// it to ~zero.
//
// Dead-connection drain enqueues records with op=-1 (payload NULL) for
// every pending seq — exactly once, on the LAST lane to exit (a
// sibling lane may still be mid-receive into a caller's zero-copy
// sink; see _ServerConn.lane_exited for the Python statement of this
// rule) — followed by a doorbell.  Queue order is preserved, so the
// death markers are always delivered after every real completion.

#include <arpa/inet.h>
#include <endian.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <strings.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <chrono>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "hist.h"
#include "wire.h"

namespace {

using bps_wire::Header;
using bps_wire::kMagic;

uint64_t steady_ns() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}


typedef void (*bpsc_cb_t)(void* ctx, int32_t op, int32_t status,
                          uint32_t flags, uint32_t seq, uint64_t key,
                          uint32_t cmd, uint32_t version,
                          const uint8_t* payload, uint64_t len,
                          int32_t zero_copied);

int connect_with_timeout(int fd, const sockaddr* sa, socklen_t slen,
                         int timeout_ms) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  int r = ::connect(fd, sa, slen);
  if (r < 0 && errno != EINPROGRESS) return -1;
  if (r < 0) {
    pollfd p{fd, POLLOUT, 0};
    if (::poll(&p, 1, timeout_ms) <= 0) return -1;
    int err = 0;
    socklen_t el = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &el) < 0 || err != 0)
      return -1;
  }
  fcntl(fd, F_SETFL, fl);  // back to blocking for the lane loops
  return 0;
}

int dial(const char* host, int port, int kind) {
  if (kind == 1) {  // uds: host is the socket path
    int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    size_t n = strlen(host);
    if (n >= sizeof(addr.sun_path)) {
      ::close(fd);
      return -1;
    }
    std::memcpy(addr.sun_path, host, n + 1);
    if (connect_with_timeout(fd, (sockaddr*)&addr, sizeof(addr), 30000) < 0) {
      ::close(fd);
      return -1;
    }
    return fd;
  }
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof portbuf, "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || !res) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect_with_timeout(fd, ai->ai_addr, (socklen_t)ai->ai_addrlen,
                             30000) == 0)
      break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

bool cli_recv_exact(int fd, void* buf, size_t n) {
  uint8_t* p = (uint8_t*)buf;
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;  // EOF or hard error
    p += r;
    n -= (size_t)r;
  }
  return true;
}

struct ClientLane {
  int fd = -1;
  std::mutex send_mu;
  std::thread th;
};

// mirrored by _DRAIN_REC_DTYPE in byteps_tpu/native/__init__.py —
// change both together (64-bit fields first: no implicit padding holes)
struct DrainRec {
  uint64_t key;
  uint64_t len;
  uint64_t off;  // arena offset of the payload (non-zero-copy only)
  int32_t op;
  int32_t status;
  uint32_t flags;
  uint32_t seq;
  uint32_t cmd;
  uint32_t version;
  int32_t zc;
  int32_t _pad;
};
static_assert(sizeof(DrainRec) == 56, "DrainRec layout drifted");

struct Completion {
  int32_t op;
  int32_t status;
  uint32_t flags;
  uint32_t seq;
  uint32_t cmd;
  uint64_t key;
  uint32_t version;
  int32_t zc;
  uint64_t len;
  std::vector<uint8_t> payload;  // owned bytes (non-zero-copy only)
};

struct NativeClient {
  std::vector<std::unique_ptr<ClientLane>> lanes;
  bpsc_cb_t cb = nullptr;
  void* cb_ctx = nullptr;

  std::mutex mu;  // seq table + lifecycle flags
  uint32_t next_seq = 0;
  struct Pending {
    uint8_t* sink;
    uint64_t sink_len;
    // send timestamp of this seq's newest attempt (0 = not sent yet):
    // feeds the native per-attempt round-trip histogram below
    uint64_t t_send_ns = 0;
  };
  std::unordered_map<uint32_t, Pending> pending;

  // Per-attempt RPC latency, measured where the wire is (send syscall →
  // completion enqueue, no ctypes trampoline / drain batching in the
  // number) — exported as native_rpc_round_trip_seconds through
  // bpsc_metrics_json and telemetry's histogram-provider seam.
  bps_hist::Hist rtt_hist;
  bool dead = false;  // set by the LAST lane to exit (after the drain)
  int live_lanes = 0;

  // end-to-end wire integrity (docs/robustness.md "Wire integrity"):
  // stamp outgoing data-plane frames (BYTEPS_WIRE_CHECKSUM, read at
  // create) and verify any response carrying kChecksumFlag; mismatches
  // across the whole striped connection count toward the teardown limit
  bool checksum_on = false;
  uint32_t ck_conn_limit = 8;
  std::atomic<uint32_t> ck_fails{0};

  // completion queue (batched delivery; see file header)
  std::mutex cq_mu;
  std::deque<Completion> cq;

  // push one completion; doorbell on the empty→non-empty transition.
  // The doorbell trampoline runs ON the calling lane thread and the
  // Python handler drains until empty, so a push into a non-empty
  // queue is always picked up by the drain loop already running.
  void push_completion(Completion&& m) {
    bool bell;
    {
      std::lock_guard<std::mutex> g(cq_mu);
      bell = cq.empty();
      cq.push_back(std::move(m));
    }
    if (bell) cb(cb_ctx, -2, 0, 0, 0, 0, 0, 0, nullptr, 0, 0);
  }

  ~NativeClient() {
    for (auto& l : lanes) {
      if (l->th.joinable()) l->th.join();
      if (l->fd >= 0) ::close(l->fd);
    }
  }

  void shutdown_all_fds() {
    // shutdown (not close) wakes lane threads blocked in recv; the fds
    // close in the destructor, after the threads are joined
    for (auto& l : lanes)
      if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
  }

  // One lane dying poisons the whole striped connection (a partially
  // striped link would strand keyed requests); only the LAST lane to
  // exit drains pending callbacks — a sibling may still be receiving
  // into a caller's zero-copy sink.
  void lane_exit() {
    shutdown_all_fds();
    std::vector<uint32_t> orphans;
    {
      std::lock_guard<std::mutex> g(mu);
      if (--live_lanes > 0) return;
      dead = true;
      orphans.reserve(pending.size());
      for (auto& kv : pending) orphans.push_back(kv.first);
      pending.clear();
    }
    for (uint32_t seq : orphans) {
      Completion m{};
      m.op = -1;
      m.status = -1;
      m.seq = seq;
      push_completion(std::move(m));
    }
  }

  void recv_loop(ClientLane* lane) {
    for (;;) {
      Header h;
      if (!cli_recv_exact(lane->fd, &h, sizeof(h))) break;
      if (h.magic != kMagic) break;  // framing desync: drop the conn
      // Optional trace context (transport.py TRACE_FLAG, status bit 7):
      // a tracing server appends 16 bytes after the header.  Consume the
      // block and clear the bit so the stream stays framed and Python
      // sees a clean status — the same optional-on-decode guarantee the
      // Python client's recv_header_ex gives (the native client stamps
      // no spans; ROADMAP keeps that as follow-up).
      uint8_t trace_ctx[16];
      bool have_trace = false;
      if (h.status & bps_wire::kTraceFlag) {
        if (!cli_recv_exact(lane->fd, trace_ctx, sizeof(trace_ctx))) break;
        h.status &= static_cast<uint8_t>(~bps_wire::kTraceFlag);
        have_trace = true;
      }
      // Optional end-to-end checksum (transport.py CHECKSUM_FLAG):
      // consume the 4-byte CRC32C and verify once the payload landed —
      // BEFORE the completion reaches the seq demux.
      uint32_t want_crc = 0;
      bool have_ck = false;
      if (h.status & bps_wire::kChecksumFlag) {
        uint8_t ckb[4];
        if (!cli_recv_exact(lane->fd, ckb, sizeof(ckb))) break;
        std::memcpy(&want_crc, ckb, 4);
        want_crc = ntohl(want_crc);
        h.status &= static_cast<uint8_t>(~bps_wire::kChecksumFlag);
        have_ck = true;
      }
      // Optional lossless container (transport.py LOSSLESS_FLAG): the
      // payload on the wire is compressed — `length` and the CRC cover
      // the compressed bytes; decode happens after integrity passes.
      bool have_lz = false;
      if (h.status & bps_wire::kLosslessFlag) {
        h.status &= static_cast<uint8_t>(~bps_wire::kLosslessFlag);
        have_lz = true;
      }
      Completion m{};
      m.op = h.op;
      m.status = h.status;
      m.flags = h.flags;
      m.seq = ntohl(h.seq);
      m.key = be64toh(h.key);
      m.cmd = ntohl(h.cmd);
      m.version = ntohl(h.version);
      m.len = be64toh(h.length);
      uint8_t* sink = nullptr;
      uint64_t sink_len = 0;
      uint64_t t_send_ns = 0;
      {
        std::lock_guard<std::mutex> g(mu);
        auto it = pending.find(m.seq);
        if (it != pending.end()) {
          sink = it->second.sink;
          sink_len = it->second.sink_len;
          t_send_ns = it->second.t_send_ns;
        }
      }
      const uint8_t* body = nullptr;
      if (m.len) {
        // a lossless frame's `length` is the container size, never the
        // caller's raw-sized sink — always land it in an owned payload
        if (!have_lz && sink && sink_len == m.len) {
          // zero-copy: the response lands directly in the caller's
          // registered buffer (ZPull-into-SArray parity); the queued
          // record carries no bytes.  The sink stays valid until the
          // drain delivers this record: Python's keep-alive is dropped
          // only by the per-record dispatch.  A checksum-rejected frame
          // may have written garbage into the sink — harmless: the
          // completion never fires, and the retried response overwrites.
          if (!cli_recv_exact(lane->fd, sink, m.len)) break;
          m.zc = 1;
          body = sink;
        } else {
          // entry-owned payload: each completion is a fresh vector (the
          // queue outlives this loop iteration), so the old per-lane
          // scratch — and its high-water-mark concern (ADVICE r4) — is
          // gone by construction
          m.payload.resize(m.len);
          if (!cli_recv_exact(lane->fd, m.payload.data(), m.len)) break;
          body = m.payload.data();
        }
      }
      if (have_ck) {
        uint32_t crc = have_trace ? bps_wire::crc32c(trace_ctx, 16) : 0;
        crc = bps_wire::crc32c(body, (size_t)m.len, crc);
        if (crc != want_crc) {
          // DROP: the pending entry stays registered (the deadline/
          // retry machinery owns healing), and Python is told via an
          // op=-3 notification record (counted, never demuxed — the
          // corrupt frame's op rides in cmd for the label)
          uint32_t fails = ck_fails.fetch_add(1, std::memory_order_relaxed) + 1;
          Completion note{};
          note.op = -3;
          note.seq = m.seq;
          note.cmd = m.op >= 0 ? (uint32_t)m.op : 0;
          push_completion(std::move(note));
          if (ck_conn_limit && fails >= ck_conn_limit)
            break;  // repeated corruption: poison the conn → revival
          continue;
        }
      }
      if (have_lz) {
        // decompress AFTER integrity passes; a corrupt container drops
        // exactly like a CRC mismatch (pending entry stays registered,
        // deadline/retry re-fetches) — the op=-3 notification carries
        // status=1 so Python counts it as wire_lossless_fail
        long raw = bps_wire::lossless_raw_len(body, (size_t)m.len);
        std::vector<uint8_t> dec;
        long got = -1;
        if (raw >= 0) {
          dec.resize(raw > 0 ? (size_t)raw : 1);
          got = bps_wire::lossless_decompress_frame(body, (size_t)m.len,
                                                    dec.data(), (size_t)raw);
        }
        if (got < 0 || got != raw) {
          uint32_t fails = ck_fails.fetch_add(1, std::memory_order_relaxed) + 1;
          Completion note{};
          note.op = -3;
          note.status = 1;
          note.seq = m.seq;
          note.cmd = m.op >= 0 ? (uint32_t)m.op : 0;
          push_completion(std::move(note));
          if (ck_conn_limit && fails >= ck_conn_limit) break;
          continue;
        }
        dec.resize((size_t)raw);
        m.payload.swap(dec);
        m.len = (uint64_t)raw;
        if (sink && sink_len == m.len) {
          // the caller registered a raw-sized sink (pull): deliver the
          // decoded bytes there so the zero-copy drain contract holds
          std::memcpy(sink, m.payload.data(), (size_t)m.len);
          m.zc = 1;
          m.payload.clear();
        }
      }
      // un-register only AFTER the payload is fully received: dying
      // mid-payload must leave the entry for the drain (op=-1), never
      // lose it
      {
        std::lock_guard<std::mutex> g(mu);
        pending.erase(m.seq);
      }
      // per-attempt round trip: payload fully landed, response not yet
      // delivered to Python (the wire-true number, retries excluded —
      // each attempt re-stamps t_send_ns)
      if (t_send_ns) rtt_hist.observe((double)(steady_ns() - t_send_ns) * 1e-9);
      push_completion(std::move(m));
    }
    lane_exit();
  }
};

// Handle registry: ids never dangle — concurrent bpsc_* calls either
// resolve the shared_ptr before bpsc_close erases it (object outlives
// the call) or fail the lookup cleanly.
std::mutex g_cli_mu;
std::map<int64_t, std::shared_ptr<NativeClient>> g_clients;
int64_t g_next_cli_id = 1;

std::shared_ptr<NativeClient> cli_for(int64_t id) {
  std::lock_guard<std::mutex> g(g_cli_mu);
  auto it = g_clients.find(id);
  return it == g_clients.end() ? nullptr : it->second;
}

// Build the pre-payload part of one outgoing frame into out: 32-byte
// header, plus the 16-byte trace-context block when trace_id != 0
// (trace ids are nonzero by construction, tracing.new_trace_id), plus
// the 4-byte CRC32C block when checksumming (BYTEPS_WIRE_CHECKSUM) —
// all through the shared wire.h build_head, the SAME encoder the
// native server's send_msg uses.  The ONE encode path bpsc_send and
// the golden-fixture shims (bps_wire_client_frame / _ck) share, so the
// live client encoder is what the byte-exact fixtures pin.  Returns
// the byte count (32..52).
size_t build_frame_head(uint8_t out[bps_wire::kMaxHeadLen], int32_t op,
                        uint32_t seq, uint64_t key, uint32_t cmd,
                        uint32_t version, uint32_t flags,
                        const void* payload, uint64_t len, uint64_t trace_id,
                        uint64_t span_id, bool checksum) {
  return bps_wire::build_head(out, (uint8_t)op, /*base_status=*/0,
                              (uint8_t)flags, seq, key, cmd, version, payload,
                              len, trace_id, span_id,
                              checksum && bps_wire::checksum_op((uint8_t)op));
}

}  // namespace

extern "C" {

int64_t bpsc_create(const char* host, int32_t port, int32_t kind,
                    int32_t streams) {
  auto c = std::make_shared<NativeClient>();
  // the shared wire.h parsers (transport.py truthiness), read at
  // create so tests toggling the env between connections see it
  c->checksum_on = bps_wire::checksum_env_on();
  c->ck_conn_limit = bps_wire::checksum_env_conn_limit();
  if (streams < 1) streams = 1;
  if (kind == 1) streams = 1;  // parity with the Python client: stripe tcp only
  for (int i = 0; i < streams; ++i) {
    int fd = dial(host, port, kind);
    if (fd < 0) return -1;  // shared_ptr frees the dialed lanes
    auto lane = std::make_unique<ClientLane>();
    lane->fd = fd;
    c->lanes.push_back(std::move(lane));
  }
  c->live_lanes = (int)c->lanes.size();
  std::lock_guard<std::mutex> g(g_cli_mu);
  int64_t id = g_next_cli_id++;
  g_clients[id] = std::move(c);
  return id;
}

void bpsc_set_cb(int64_t h, void (*cb)(void*, int32_t, int32_t, uint32_t,
                                       uint32_t, uint64_t, uint32_t, uint32_t,
                                       const uint8_t*, uint64_t, int32_t),
                 void* ctx) {
  auto c = cli_for(h);
  if (!c) return;
  c->cb = cb;
  c->cb_ctx = ctx;
  // lanes start only once the callback is in place — a response racing
  // set_cb could otherwise fire a null pointer
  NativeClient* cp = c.get();
  for (auto& l : c->lanes) {
    ClientLane* lp = l.get();
    l->th = std::thread([cp, lp] { cp->recv_loop(lp); });
  }
}

int64_t bpsc_alloc_seq(int64_t h, void* sink, uint64_t sink_len) {
  auto c = cli_for(h);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  if (c->dead) return -1;
  uint32_t seq = c->next_seq++;
  c->pending[seq] = {(uint8_t*)sink, sink_len};
  return (int64_t)seq;
}

// Trace-context-aware send (docs/observability.md): trace_id/span_id
// ride the optional 16-byte TRACE_FLAG block after the header, exactly
// as transport.py Message.encode emits it — the Python engine's span
// context now propagates through the native client too, so the server's
// child spans join the worker spans whichever client implementation
// carried the frame.  trace_id 0 = untraced frame (the ids are nonzero
// by construction).
int32_t bpsc_send2(int64_t h, int32_t op, uint32_t seq, uint64_t key,
                   uint32_t cmd, uint32_t version, uint32_t flags,
                   const void* payload, uint64_t len, uint64_t trace_id,
                   uint64_t span_id) {
  auto c = cli_for(h);
  if (!c) return -1;
  ClientLane* lane = c->lanes[key % c->lanes.size()].get();
  // the shared wire.h codec — one header encoder for client, server,
  // and the golden-fixture shim (Op.FUSED / RESYNC frames ride this
  // same path: the native client is payload-agnostic, so the fused
  // pack and recovery-plane routing in comm/ps_client.py work over
  // either client implementation)
  uint8_t head[bps_wire::kMaxHeadLen];
  size_t head_len = build_frame_head(head, op, seq, key, cmd, version, flags,
                                     payload, len, trace_id, span_id,
                                     c->checksum_on);
  // per-attempt latency starts at the send, transport included —
  // re-stamped on every retry attempt (the Python client's t_sent
  // placement); registered seq only, control sends have no entry
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->pending.find(seq);
    if (it != c->pending.end()) it->second.t_send_ns = steady_ns();
  }
  // scatter-gather send: header + payload leave through one writev with
  // zero payload memcpys (transport.py sendmsg parity)
  iovec iov[2] = {{head, head_len}, {const_cast<void*>(payload), len}};
  int iovcnt = len ? 2 : 1;
  size_t off = 0, total = head_len + (size_t)len;
  std::lock_guard<std::mutex> g(lane->send_mu);
  while (off < total) {
    iovec cur[2];
    int n = 0;
    size_t skip = off;
    for (int i = 0; i < iovcnt; ++i) {
      if (skip >= iov[i].iov_len) {
        skip -= iov[i].iov_len;
        continue;
      }
      cur[n].iov_base = (uint8_t*)iov[i].iov_base + skip;
      cur[n].iov_len = iov[i].iov_len - skip;
      skip = 0;
      ++n;
    }
    ssize_t w = ::writev(lane->fd, cur, n);
    if (w < 0 && errno == EINTR) continue;
    if (w <= 0) return -1;
    off += (size_t)w;
  }
  return 0;
}

// pre-observability surface: an untraced bpsc_send2 (kept so an older
// Python layer over a fresh .so keeps working)
int32_t bpsc_send(int64_t h, int32_t op, uint32_t seq, uint64_t key,
                  uint32_t cmd, uint32_t version, uint32_t flags,
                  const void* payload, uint64_t len) {
  return bpsc_send2(h, op, seq, key, cmd, version, flags, payload, len, 0, 0);
}

// One client handle's histograms as a JSON document (same shape as
// bps_native_server_metrics_json) — parsed by native/__init__.py and
// fed through telemetry's histogram-provider seam so the native data
// plane's rpc_round_trip lands in get_metrics()/Prometheus/the cluster
// aggregate.  Returns bytes written, -(needed) when cap is too small,
// or -1 for an unknown handle.
int64_t bpsc_metrics_json(int64_t h, uint8_t* out, uint64_t cap) {
  auto c = cli_for(h);
  if (!c) return -1;
  std::string body = "{\"histograms\": [";
  c->rtt_hist.append_json(&body, "native_rpc_round_trip_seconds", nullptr,
                          "");
  body += "]}";
  if (body.size() > cap) return -(int64_t)body.size();
  std::memcpy(out, body.data(), body.size());
  return (int64_t)body.size();
}

// Golden-fixture shim (tests/test_wire_golden.py): emit one complete
// frame (header [+ trace block] + payload) through the LIVE client
// encode path (build_frame_head — the same bytes bpsc_send2 writes), so
// transport.py Message.encode and the native client cannot drift.
// Returns bytes written or -(needed) when cap is too small.
int64_t bps_wire_client_frame(int32_t op, uint32_t seq, uint64_t key,
                              uint32_t cmd, uint32_t version, uint32_t flags,
                              uint64_t trace_id, uint64_t span_id,
                              const uint8_t* payload, uint64_t len,
                              uint8_t* out, uint64_t cap) {
  uint8_t head[bps_wire::kMaxHeadLen];
  size_t head_len = build_frame_head(head, op, seq, key, cmd, version, flags,
                                     payload, len, trace_id, span_id,
                                     /*checksum=*/false);
  uint64_t total = head_len + len;
  if (total > cap) return -(int64_t)total;
  std::memcpy(out, head, head_len);
  if (len) std::memcpy(out + head_len, payload, len);
  return (int64_t)total;
}

// Checksummed twin of bps_wire_client_frame: the same LIVE encode path
// with BYTEPS_WIRE_CHECKSUM semantics forced on — what the checksummed
// golden stream (tests/test_wire_golden.py CHECKSUM_GOLDEN_SHA256)
// pins against transport.py.  A separate symbol so the original shim's
// bytes (and its callers' bound signature) never change.
int64_t bps_wire_client_frame_ck(int32_t op, uint32_t seq, uint64_t key,
                                 uint32_t cmd, uint32_t version,
                                 uint32_t flags, uint64_t trace_id,
                                 uint64_t span_id, const uint8_t* payload,
                                 uint64_t len, uint8_t* out, uint64_t cap) {
  uint8_t head[bps_wire::kMaxHeadLen];
  size_t head_len = build_frame_head(head, op, seq, key, cmd, version, flags,
                                     payload, len, trace_id, span_id,
                                     /*checksum=*/true);
  uint64_t total = head_len + len;
  if (total > cap) return -(int64_t)total;
  std::memcpy(out, head, head_len);
  if (len) std::memcpy(out + head_len, payload, len);
  return (int64_t)total;
}

// The shared CRC32C through the LIVE wire.h implementation — the ctypes
// fast path transport.py crc32c() rides, and the parity anchor the
// integrity tests pin the pure-Python fallback against.
uint32_t bps_wire_crc32c(const void* data, uint64_t n, uint32_t crc) {
  return bps_wire::crc32c(data, (size_t)n, crc);
}

// Lossless frame codec through the LIVE wire.h implementation — the
// ctypes fast path compression/lossless.py rides, and the parity anchor
// tests/test_lossless.py pins the pure-Python codec against (both sides
// must emit identical containers for identical inputs).  Returns the
// container / raw size, -1 on decode failure, 0 when `cap` is too small.
int64_t bps_wire_lossless_compress(const uint8_t* src, uint64_t n,
                                   uint8_t* dst, uint64_t cap) {
  return (int64_t)bps_wire::lossless_compress_frame(src, (size_t)n, dst,
                                                    (size_t)cap);
}

int64_t bps_wire_lossless_decompress(const uint8_t* src, uint64_t n,
                                     uint8_t* dst, uint64_t dst_cap) {
  return (int64_t)bps_wire::lossless_decompress_frame(src, (size_t)n, dst,
                                                      (size_t)dst_cap);
}

int64_t bpsc_drain(int64_t h, void* recs_out, int64_t max_recs,
                   void* arena_out, uint64_t arena_cap) {
  auto c = cli_for(h);
  if (!c) return 0;
  DrainRec* recs = (DrainRec*)recs_out;
  uint8_t* arena = (uint8_t*)arena_out;
  uint64_t used = 0;
  int64_t n = 0;
  std::lock_guard<std::mutex> g(c->cq_mu);
  while (n < max_recs && !c->cq.empty()) {
    Completion& m = c->cq.front();
    uint64_t need = m.zc ? 0 : m.payload.size();
    if (need > arena_cap - used) {
      if (n > 0) break;  // deliver what fits; caller loops
      return -(int64_t)need;  // first record too big: grow + retry
    }
    DrainRec& r = recs[n];
    r.key = m.key;
    r.len = m.len;
    r.off = used;
    r.op = m.op;
    r.status = m.status;
    r.flags = m.flags;
    r.seq = m.seq;
    r.cmd = m.cmd;
    r.version = m.version;
    r.zc = m.zc;
    r._pad = 0;
    if (need) {
      std::memcpy(arena + used, m.payload.data(), need);
      used += need;
    }
    c->cq.pop_front();
    ++n;
  }
  return n;
}

void bpsc_close(int64_t h) {
  std::shared_ptr<NativeClient> c;
  {
    std::lock_guard<std::mutex> g(g_cli_mu);
    auto it = g_clients.find(h);
    if (it == g_clients.end()) return;  // idempotent
    c = std::move(it->second);
    g_clients.erase(it);
  }
  c->shutdown_all_fds();  // wakes lane threads; they drain and exit
  for (auto& l : c->lanes)
    if (l->th.joinable()) l->th.join();
  // final flush: the handle is already out of the registry, so the
  // doorbell→bpsc_drain contract can no longer deliver — push anything
  // still queued (incl. the lane-exit op=-1 death markers) through the
  // per-record trampoline instead.  Cold path; per-message cost fine.
  // Without this, a blocking request pending at close would hang on a
  // cb(None) that never fires.
  std::deque<Completion> leftover;
  {
    std::lock_guard<std::mutex> g(c->cq_mu);
    leftover.swap(c->cq);
  }
  for (auto& m : leftover) {
    const uint8_t* p =
        (!m.zc && !m.payload.empty()) ? m.payload.data() : nullptr;
    c->cb(c->cb_ctx, m.op, m.status, m.flags, m.seq, m.key, m.cmd,
          m.version, p, m.len, m.zc);
  }
  // fds close in ~NativeClient once any in-flight bpsc_send releases
  // its shared_ptr
}

}  // extern "C"
