// Native worker-side PS data plane (the C++ half of comm/ps_client.py).
//
// The reference's worker hot path is C++ (core_loops.cc:538-618: ZPush /
// ZPull framing, completion demux, zero-copy into caller SArrays) with
// Python only steering.  This file gives the TPU build the same split:
// lane sockets, 32-byte framing, seq-table demux, and payload receive —
// including pull-into-caller-buffer zero copy — all run on C++ threads
// with no GIL; Python sees one completion callback per message (ctypes
// re-acquires the GIL for the duration of the callback only).
//
// Scope: the worker↔server DATA lanes for the tcp and uds vans,
// including BYTEPS_TCP_STREAMS striping (responses demux into one shared
// seq table, per-key lane pinning preserves per-key FIFO).  The shm van
// keeps its Python client (its bulk path is already syscall-free mmap
// memcpy), and the scheduler link stays Python (low-rate control plane).
//
// Contract with comm/ps_client.py (_NativeServerConn):
//   h   = bpsc_create(host, port, kind, streams)   kind: 0 tcp, 1 uds
//         bpsc_set_cb(h, cb, ctx)                  BEFORE first alloc/send
//   seq = bpsc_alloc_seq(h, sink_ptr, sink_len)    -1 => conn dead
//         bpsc_send(h, op, seq, key, cmd, ver, flags, payload, len)
//         bpsc_close(h)                            joins lanes, frees h
//
// Handles are ids into a global registry holding shared_ptrs: a send
// racing a close (elastic server-swap failure path) resolves its id
// before the close erases it — the object stays alive until the last
// in-flight call returns — or after, in which case the call fails
// cleanly instead of touching freed memory.
//
// Completion callback (one per response, fired from a lane thread):
//   cb(ctx, op, status, flags, seq, key, cmd, version, payload, len, zc)
// zc=1: payload landed in the caller's registered sink (ptr = sink).
// Dead-connection drain fires cb with status=-1, payload=NULL for every
// pending seq — exactly once, on the LAST lane to exit (a sibling lane
// may still be mid-receive into a caller's zero-copy sink; see
// _ServerConn.lane_exited for the Python statement of this rule).

#include <arpa/inet.h>
#include <endian.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "wire.h"

namespace {

using bps_wire::Header;
using bps_wire::kMagic;

typedef void (*bpsc_cb_t)(void* ctx, int32_t op, int32_t status,
                          uint32_t flags, uint32_t seq, uint64_t key,
                          uint32_t cmd, uint32_t version,
                          const uint8_t* payload, uint64_t len,
                          int32_t zero_copied);

int connect_with_timeout(int fd, const sockaddr* sa, socklen_t slen,
                         int timeout_ms) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  int r = ::connect(fd, sa, slen);
  if (r < 0 && errno != EINPROGRESS) return -1;
  if (r < 0) {
    pollfd p{fd, POLLOUT, 0};
    if (::poll(&p, 1, timeout_ms) <= 0) return -1;
    int err = 0;
    socklen_t el = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &el) < 0 || err != 0)
      return -1;
  }
  fcntl(fd, F_SETFL, fl);  // back to blocking for the lane loops
  return 0;
}

int dial(const char* host, int port, int kind) {
  if (kind == 1) {  // uds: host is the socket path
    int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    size_t n = strlen(host);
    if (n >= sizeof(addr.sun_path)) {
      ::close(fd);
      return -1;
    }
    std::memcpy(addr.sun_path, host, n + 1);
    if (connect_with_timeout(fd, (sockaddr*)&addr, sizeof(addr), 30000) < 0) {
      ::close(fd);
      return -1;
    }
    return fd;
  }
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof portbuf, "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || !res) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect_with_timeout(fd, ai->ai_addr, (socklen_t)ai->ai_addrlen,
                             30000) == 0)
      break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd >= 0) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  return fd;
}

bool cli_recv_exact(int fd, void* buf, size_t n) {
  uint8_t* p = (uint8_t*)buf;
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;  // EOF or hard error
    p += r;
    n -= (size_t)r;
  }
  return true;
}

struct ClientLane {
  int fd = -1;
  std::mutex send_mu;
  std::thread th;
};

struct NativeClient {
  std::vector<std::unique_ptr<ClientLane>> lanes;
  bpsc_cb_t cb = nullptr;
  void* cb_ctx = nullptr;

  std::mutex mu;  // seq table + lifecycle flags
  uint32_t next_seq = 0;
  struct Pending {
    uint8_t* sink;
    uint64_t sink_len;
  };
  std::unordered_map<uint32_t, Pending> pending;
  bool dead = false;  // set by the LAST lane to exit (after the drain)
  int live_lanes = 0;

  ~NativeClient() {
    for (auto& l : lanes) {
      if (l->th.joinable()) l->th.join();
      if (l->fd >= 0) ::close(l->fd);
    }
  }

  void shutdown_all_fds() {
    // shutdown (not close) wakes lane threads blocked in recv; the fds
    // close in the destructor, after the threads are joined
    for (auto& l : lanes)
      if (l->fd >= 0) ::shutdown(l->fd, SHUT_RDWR);
  }

  // One lane dying poisons the whole striped connection (a partially
  // striped link would strand keyed requests); only the LAST lane to
  // exit drains pending callbacks — a sibling may still be receiving
  // into a caller's zero-copy sink.
  void lane_exit() {
    shutdown_all_fds();
    std::vector<uint32_t> orphans;
    {
      std::lock_guard<std::mutex> g(mu);
      if (--live_lanes > 0) return;
      dead = true;
      orphans.reserve(pending.size());
      for (auto& kv : pending) orphans.push_back(kv.first);
      pending.clear();
    }
    for (uint32_t seq : orphans)
      cb(cb_ctx, -1, -1, 0, seq, 0, 0, 0, nullptr, 0, 0);
  }

  void recv_loop(ClientLane* lane) {
    std::vector<uint8_t> scratch;
    for (;;) {
      Header h;
      if (!cli_recv_exact(lane->fd, &h, sizeof(h))) break;
      if (h.magic != kMagic) break;  // framing desync: drop the conn
      uint32_t seq = ntohl(h.seq);
      uint64_t key = be64toh(h.key);
      uint64_t len = be64toh(h.length);
      uint8_t* sink = nullptr;
      uint64_t sink_len = 0;
      {
        std::lock_guard<std::mutex> g(mu);
        auto it = pending.find(seq);
        if (it != pending.end()) {
          sink = it->second.sink;
          sink_len = it->second.sink_len;
        }
      }
      const uint8_t* payload = nullptr;
      int32_t zc = 0;
      if (len) {
        if (sink && sink_len == len) {
          // zero-copy: the response lands directly in the caller's
          // registered buffer (ZPull-into-SArray parity)
          if (!cli_recv_exact(lane->fd, sink, len)) break;
          payload = sink;
          zc = 1;
        } else {
          scratch.resize(len);
          if (!cli_recv_exact(lane->fd, scratch.data(), len)) break;
          payload = scratch.data();
        }
      }
      // un-register only AFTER the payload is fully received: dying
      // mid-payload must leave the entry for the drain (cb status=-1),
      // never lose it
      {
        std::lock_guard<std::mutex> g(mu);
        pending.erase(seq);
      }
      cb(cb_ctx, h.op, h.status, h.flags, seq, key, ntohl(h.cmd),
         ntohl(h.version), payload, len, zc);
      // a rare oversized non-zero-copy response must not pin its high-
      // water mark per lane for the connection's lifetime (ADVICE r4):
      // the callback consumed the payload synchronously, so release the
      // scratch now (the common big-payload path is zero-copy and never
      // touches scratch at all)
      constexpr size_t kScratchKeep = size_t(1) << 20;
      if (scratch.capacity() > kScratchKeep) {
        std::vector<uint8_t>().swap(scratch);
      }
    }
    lane_exit();
  }
};

// Handle registry: ids never dangle — concurrent bpsc_* calls either
// resolve the shared_ptr before bpsc_close erases it (object outlives
// the call) or fail the lookup cleanly.
std::mutex g_cli_mu;
std::map<int64_t, std::shared_ptr<NativeClient>> g_clients;
int64_t g_next_cli_id = 1;

std::shared_ptr<NativeClient> cli_for(int64_t id) {
  std::lock_guard<std::mutex> g(g_cli_mu);
  auto it = g_clients.find(id);
  return it == g_clients.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t bpsc_create(const char* host, int32_t port, int32_t kind,
                    int32_t streams) {
  auto c = std::make_shared<NativeClient>();
  if (streams < 1) streams = 1;
  if (kind == 1) streams = 1;  // parity with the Python client: stripe tcp only
  for (int i = 0; i < streams; ++i) {
    int fd = dial(host, port, kind);
    if (fd < 0) return -1;  // shared_ptr frees the dialed lanes
    auto lane = std::make_unique<ClientLane>();
    lane->fd = fd;
    c->lanes.push_back(std::move(lane));
  }
  c->live_lanes = (int)c->lanes.size();
  std::lock_guard<std::mutex> g(g_cli_mu);
  int64_t id = g_next_cli_id++;
  g_clients[id] = std::move(c);
  return id;
}

void bpsc_set_cb(int64_t h, void (*cb)(void*, int32_t, int32_t, uint32_t,
                                       uint32_t, uint64_t, uint32_t, uint32_t,
                                       const uint8_t*, uint64_t, int32_t),
                 void* ctx) {
  auto c = cli_for(h);
  if (!c) return;
  c->cb = cb;
  c->cb_ctx = ctx;
  // lanes start only once the callback is in place — a response racing
  // set_cb could otherwise fire a null pointer
  NativeClient* cp = c.get();
  for (auto& l : c->lanes) {
    ClientLane* lp = l.get();
    l->th = std::thread([cp, lp] { cp->recv_loop(lp); });
  }
}

int64_t bpsc_alloc_seq(int64_t h, void* sink, uint64_t sink_len) {
  auto c = cli_for(h);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  if (c->dead) return -1;
  uint32_t seq = c->next_seq++;
  c->pending[seq] = {(uint8_t*)sink, sink_len};
  return (int64_t)seq;
}

int32_t bpsc_send(int64_t h, int32_t op, uint32_t seq, uint64_t key,
                  uint32_t cmd, uint32_t version, uint32_t flags,
                  const void* payload, uint64_t len) {
  auto c = cli_for(h);
  if (!c) return -1;
  ClientLane* lane = c->lanes[key % c->lanes.size()].get();
  Header hd;
  hd.magic = kMagic;
  hd.op = (uint8_t)op;
  hd.status = 0;
  hd.flags = (uint8_t)flags;
  hd.seq = htonl(seq);
  hd.key = htobe64(key);
  hd.cmd = htonl(cmd);
  hd.version = htonl(version);
  hd.length = htobe64(len);
  // scatter-gather send: header + payload leave through one writev with
  // zero payload memcpys (transport.py sendmsg parity)
  iovec iov[2] = {{&hd, sizeof(hd)}, {const_cast<void*>(payload), len}};
  int iovcnt = len ? 2 : 1;
  size_t off = 0, total = sizeof(hd) + (size_t)len;
  std::lock_guard<std::mutex> g(lane->send_mu);
  while (off < total) {
    iovec cur[2];
    int n = 0;
    size_t skip = off;
    for (int i = 0; i < iovcnt; ++i) {
      if (skip >= iov[i].iov_len) {
        skip -= iov[i].iov_len;
        continue;
      }
      cur[n].iov_base = (uint8_t*)iov[i].iov_base + skip;
      cur[n].iov_len = iov[i].iov_len - skip;
      skip = 0;
      ++n;
    }
    ssize_t w = ::writev(lane->fd, cur, n);
    if (w < 0 && errno == EINTR) continue;
    if (w <= 0) return -1;
    off += (size_t)w;
  }
  return 0;
}

void bpsc_close(int64_t h) {
  std::shared_ptr<NativeClient> c;
  {
    std::lock_guard<std::mutex> g(g_cli_mu);
    auto it = g_clients.find(h);
    if (it == g_clients.end()) return;  // idempotent
    c = std::move(it->second);
    g_clients.erase(it);
  }
  c->shutdown_all_fds();  // wakes lane threads; they drain and exit
  for (auto& l : c->lanes)
    if (l->th.joinable()) l->th.join();
  // fds close in ~NativeClient once any in-flight bpsc_send releases
  // its shared_ptr
}

}  // extern "C"
